"""Pluggable capacity policies — who waits, who's preempted, which slice.

A policy answers four questions the admitter/scheduler mechanism asks:

  * `order_waiting`   — in what order do waiting gangs claim free slices?
  * `may_reserve`     — may this gang reserve *now* (tenant caps)?
  * `choose_slices`   — among matching free slices, which to take? (the
                        Gavel-style heterogeneity hook: price a gang's
                        demand against each candidate generation)
  * `select_victims`  — which running gangs may be preempted to unblock a
                        starved demander?

Policies are stateless over the arguments they receive: `usage` (tenant ->
chips reserved) and `total_chips` are computed by the caller, so the hooks
are safe to call from under the admitter's lock (they only touch the
leaf-locked TenantQuotas). All hooks receive gang *state* objects duck-
typed as: tenant, priority, seq, preemptions, tpu_chips, num_slices.
"""
from __future__ import annotations

import abc
from typing import Dict, List, Optional

from kubedl_tpu.executor.tpu_topology import SliceInfo, parse_slice_type
from kubedl_tpu.sched.quota import TenantQuotas

# Relative per-chip training throughput by TPU generation — the Gavel
# pricing table (PAPERS.md: heterogeneity-aware policies normalize demand
# by effective throughput on each accelerator type). Coarse but ordered
# correctly; refine per-model when profiles exist.
THROUGHPUT_PER_CHIP = {"v4": 1.0, "v5e": 0.9, "v5p": 2.0, "v6e": 2.5}


def slice_cost(info: SliceInfo) -> float:
    """A slice's price in normalized throughput units."""
    return info.type.chips * THROUGHPUT_PER_CHIP.get(info.type.generation, 1.0)


def demand_chips(gang) -> int:
    """Chips a reservation for this gang would take, best-effort: the
    requested slice shape's size when declared (the gang gets whole
    slices), else its summed container requests."""
    if getattr(gang, "requested_slice", ""):
        try:
            return parse_slice_type(gang.requested_slice).chips * max(
                getattr(gang, "num_slices", 1), 1
            )
        except ValueError:
            pass
    return int(gang.tpu_chips)


class CapacityPolicy(abc.ABC):
    name = ""

    def __init__(self, quotas: Optional[TenantQuotas] = None) -> None:
        self.quotas = quotas or TenantQuotas()

    # -- ordering --------------------------------------------------------

    def order_waiting(self, waiting: List, usage: Dict[str, int], total_chips: int) -> List:
        """Default: (priority desc, FIFO) — the admitter's historical order."""
        return sorted(waiting, key=lambda s: (-s.priority, s.seq))

    # -- admission gates -------------------------------------------------

    def may_reserve(self, gang, usage: Dict[str, int], total_chips: int) -> bool:
        """Tenant cap: a HARD ceiling — the grant itself must fit, so a
        single large gang can't blow past the cap from below it. The
        caller must NOT shield slices for a gang this rejects."""
        cap = self.quotas.cap(gang.tenant)
        if cap is None:
            return True
        return usage.get(gang.tenant, 0) + demand_chips(gang) <= cap

    # -- slice choice ----------------------------------------------------

    def choose_slices(self, gang, candidates: List[SliceInfo], n: int) -> Optional[List[SliceInfo]]:
        """None = caller's default (tightest chip fit first)."""
        return None

    # -- preemption ------------------------------------------------------

    def select_victims(self, demander, holders: List, usage: Dict[str, int], total_chips: int) -> List:
        """Ordered victim candidates from `holders` (running gangs whose
        reserved slices match the demander's demand). Empty = never
        preempt under this policy."""
        return []


class FifoPolicy(CapacityPolicy):
    """Strict arrival order; priorities ignored; no preemption."""

    name = "fifo"

    def order_waiting(self, waiting, usage, total_chips):
        return sorted(waiting, key=lambda s: s.seq)


class PriorityPolicy(CapacityPolicy):
    """(priority desc, FIFO) ordering; a strictly-higher-priority demander
    may evict lower-priority running gangs — lowest priority first,
    youngest first among equals (least work lost)."""

    name = "priority"

    def select_victims(self, demander, holders, usage, total_chips):
        victims = [h for h in holders if h.priority < demander.priority]
        return sorted(victims, key=lambda h: (h.priority, -h.seq))


class FairSharePolicy(CapacityPolicy):
    """Weighted max-min: waiting gangs of the most under-served tenant
    (lowest usage/fair-share ratio) claim freed slices first; an
    under-share demander may evict gangs of over-share tenants."""

    name = "fair_share"

    def _active(self, gangs, usage) -> List[str]:
        return list({g.tenant for g in gangs} | set(usage))

    def order_waiting(self, waiting, usage, total_chips):
        active = self._active(waiting, usage)
        shares = self.quotas.fair_shares(active, total_chips)
        return sorted(
            waiting,
            key=lambda s: (
                self.quotas.share_ratio(s.tenant, usage, shares),
                -s.priority,
                s.seq,
            ),
        )

    def select_victims(self, demander, holders, usage, total_chips):
        active = self._active([demander] + holders, usage)
        shares = self.quotas.fair_shares(active, total_chips)
        if self.quotas.share_ratio(demander.tenant, usage, shares) >= 1.0:
            return []  # the demander is already at/over its share
        victims = [
            h for h in holders
            if h.tenant != demander.tenant
            and self.quotas.share_ratio(h.tenant, usage, shares) > 1.0
        ]
        # most over-served tenant first, then lowest priority, then youngest
        return sorted(
            victims,
            key=lambda h: (
                -self.quotas.share_ratio(h.tenant, usage, shares),
                h.priority,
                -h.seq,
            ),
        )


class GavelPolicy(PriorityPolicy):
    """Heterogeneity-aware slice pricing on top of priority ordering:
    among matching free slices, take the cheapest adequate hardware in
    normalized-throughput units (THROUGHPUT_PER_CHIP), keeping
    high-throughput generations free for demand that needs them."""

    name = "gavel"

    def choose_slices(self, gang, candidates, n):
        if len(candidates) < n:
            return None
        return sorted(candidates, key=slice_cost)[:n]


_POLICIES = {p.name: p for p in (FifoPolicy, PriorityPolicy, FairSharePolicy, GavelPolicy)}


def policy_names() -> List[str]:
    return sorted(_POLICIES)


def make_policy(name: str, quotas: Optional[TenantQuotas] = None) -> CapacityPolicy:
    cls = _POLICIES.get(name)
    if cls is None:
        raise ValueError(f"unknown scheduler policy {name!r} (have: {policy_names()})")
    return cls(quotas)
