"""Tenant quota accounting — weights, caps, fair shares, chip-seconds.

Tenants come from the `kubedl.io/tenancy` annotation (utils/tenancy.py);
jobs without one are pooled under the "default" tenant. A tenant's fair
share is its weighted fraction of the pool's chips over the tenants that
are *active* (running or queued) — an idle tenant's weight does not strand
capacity. Caps are hard ceilings: once a tenant's chips-in-use reaches its
cap, the admitter stops granting it new reservations (waiting gangs stay
queued without shielding slices from others).
"""
from __future__ import annotations

import math
import threading

from kubedl_tpu.analysis.witness import new_lock
from typing import Dict, Iterable, Optional

DEFAULT_TENANT = "default"


def normalize_tenant(tenant: str) -> str:
    return tenant or DEFAULT_TENANT


class TenantQuotas:
    """Static config (weights/caps) + accumulated usage counters.

    The counters are leaf-locked so policy hooks may read them from under
    the admitter's lock without ordering hazards.
    """

    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        caps: Optional[Dict[str, int]] = None,
        default_weight: float = 1.0,
    ) -> None:
        for name, w in (weights or {}).items():
            if not math.isfinite(float(w)) or float(w) <= 0:
                raise ValueError(
                    f"tenant weight must be finite and > 0, got {name}={w} "
                    f"(a negative or NaN weight would corrupt every other "
                    f"tenant's fair share)")
        for name, c in (caps or {}).items():
            if int(c) < 0:
                raise ValueError(f"tenant cap must be >= 0, got {name}={c}")
        self._weights = {normalize_tenant(k): float(v) for k, v in (weights or {}).items()}
        self._caps = {normalize_tenant(k): int(v) for k, v in (caps or {}).items()}
        self.default_weight = float(default_weight)
        self._lock = new_lock("sched.quota.TenantQuotas._lock")
        self._chip_seconds: Dict[str, float] = {}
        self._preemptions: Dict[str, int] = {}
        self._rev = 0  # bumps when the counters above actually move

    # -- config reads ----------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self._weights.get(normalize_tenant(tenant), self.default_weight)

    def cap(self, tenant: str) -> Optional[int]:
        return self._caps.get(normalize_tenant(tenant))

    def fair_shares(
        self, active_tenants: Iterable[str], total_chips: int
    ) -> Dict[str, float]:
        """Weighted fair share of the pool, in chips, per active tenant."""
        active = sorted({normalize_tenant(t) for t in active_tenants})
        total_weight = sum(self.weight(t) for t in active)
        if not active or total_weight <= 0:
            return {}
        return {t: total_chips * self.weight(t) / total_weight for t in active}

    def share_ratio(
        self, tenant: str, usage: Dict[str, int], shares: Dict[str, float]
    ) -> float:
        """chips-in-use / fair-share; >1 means over-served. A tenant with
        no share (weight 0) counts as infinitely over-served."""
        tenant = normalize_tenant(tenant)
        share = shares.get(tenant, 0.0)
        used = usage.get(tenant, 0)
        if share <= 0:
            return float("inf") if used else 0.0
        return used / share

    # -- accounting ------------------------------------------------------

    def accrue(self, usage: Dict[str, int], dt: float) -> None:
        """Integrate chips-in-use over `dt` seconds into chip-seconds."""
        if dt <= 0:
            return
        with self._lock:
            accrued = False
            for tenant, chips in usage.items():
                if chips <= 0:
                    continue
                t = normalize_tenant(tenant)
                self._chip_seconds[t] = self._chip_seconds.get(t, 0.0) + chips * dt
                accrued = True
            if accrued:
                self._rev += 1

    def note_preemption(self, tenant: str) -> None:
        with self._lock:
            t = normalize_tenant(tenant)
            self._preemptions[t] = self._preemptions.get(t, 0) + 1
            self._rev += 1

    def version(self) -> int:
        """Change token for the metrics render cache: moves whenever the
        accumulated counters moved (an idle fleet accrues nothing, so its
        token — and the rendered text — stays put)."""
        with self._lock:
            return self._rev

    def preemptions(self, tenant: str) -> int:
        with self._lock:
            return self._preemptions.get(normalize_tenant(tenant), 0)

    # -- exposition ------------------------------------------------------

    def snapshot(
        self,
        usage: Dict[str, int],
        total_chips: int,
        active_tenants: Iterable[str],
    ) -> Dict[str, Dict]:
        """Per-tenant state for metrics/CLI: usage, share, fair share,
        chip-seconds, preemptions."""
        shares = self.fair_shares(active_tenants, total_chips)
        with self._lock:
            tenants = sorted(
                {normalize_tenant(t) for t in active_tenants}
                | set(self._chip_seconds) | set(self._preemptions)
            )
            out = {}
            for t in tenants:
                used = usage.get(t, 0)
                out[t] = {
                    "weight": self.weight(t),
                    "cap_chips": self.cap(t),
                    "chips_in_use": used,
                    "fair_share_chips": round(shares.get(t, 0.0), 3),
                    "share": round(used / total_chips, 4) if total_chips else 0.0,
                    "chip_seconds": round(self._chip_seconds.get(t, 0.0), 3),
                    "preemptions": self._preemptions.get(t, 0),
                }
            return out
