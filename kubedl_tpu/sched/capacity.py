"""The capacity scheduler — fair-share admission, active preemption,
elastic slice resizing (docs/scheduling.md).

Sits between the reconciler engine and the gang admitter: the admitter
executes reserve/evict/resize directives; this scheduler decides them on a
periodic tick (wired as a manager loop, core/manager.py add_loop). Three
pillars:

  * tenant fair-share — per-tenant weights/caps (sched/quota.py) drive the
    waiting-queue order and admission gates through the pluggable policy
    (sched/policy.py: fifo | priority | fair_share | gavel);
  * active preemption — when a policy-favored gang waits on a full pool,
    victims are selected by policy and driven through the existing
    checkpoint-then-evict path: the admitter releases their slices with a
    requeue backoff, then the victims' pods are DELETED — the local
    executor SIGTERMs the trainer, which saves an Orbax checkpoint
    (train/trainer.py); the engine recreates the pods, which sit Pending
    until re-admission, where the trainer restores (the machinery
    test_preemption_resume.py exercises);
  * elastic resize — a job declaring admissible fallback shapes
    (SchedulingPolicy.tpu_slice_fallbacks) is re-targeted at a smaller
    shape when its preferred one stays unavailable (Tenplex-style
    shape-agnostic restore in the trainer), and grown back when capacity
    frees up.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional

from kubedl_tpu.core.store import NotFound
from kubedl_tpu.gang.interface import (
    ANNOTATION_GANG_NAME,
    CapacityDirector,
    GangSnapshot,
    gang_pods,
)
from kubedl_tpu.sched.policy import make_policy
from kubedl_tpu.sched.quota import TenantQuotas
from kubedl_tpu.analysis.witness import new_lock

log = logging.getLogger("kubedl_tpu.sched")


@dataclass
class CapacityConfig:
    policy: str = "priority"  # fifo | priority | fair_share | gavel
    # delta-maintained demand mirror (docs/control_plane_scale.md): a
    # tick folds admitter deltas instead of re-snapshotting the whole
    # fleet; False restores the full-rescan path (the parity oracle)
    incremental_demand_view: bool = True
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    tenant_caps: Dict[str, int] = field(default_factory=dict)
    enable_preemption: bool = True
    # victim requeue pacing: hold = backoff * 2^min(preemptions, 6), capped
    preemption_backoff: float = 0.5
    preemption_max_backoff: float = 30.0
    enable_elastic: bool = True
    # how long a gang waits at an unavailable shape before shrinking to a
    # declared fallback, and how long it runs degraded before growing back
    shrink_delay: float = 0.5
    grow_delay: float = 2.0
    # eviction drain safety valve: evicted slices free at the latest this
    # many seconds after the eviction if pod-exit confirmations never
    # arrive (real-kubelet mode); the local executor confirms in ~the
    # SIGTERM grace. Must exceed the executor's grace window.
    drain_timeout: float = 30.0
    # live reshard (docs/scheduling.md "Live resharding"): how long the
    # scheduler waits for every pod's RESIZE reply before declaring the
    # reshard failed and falling back closed to checkpoint-then-evict,
    # and the quiesce budget passed down to the gang's staged lane
    reshard_reply_timeout: float = 20.0
    quiesce_timeout: float = 30.0


# resize-downtime histogram bucket bounds (seconds): live reshards land in
# the low buckets, checkpoint-restore fallbacks in the tens-of-seconds tail
RESHARD_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0)


@dataclass
class _PendingReshard:
    """One issued RESIZE awaiting its pods' replies."""

    gang_key: str
    replies: List[str]  # absolute reply paths, one per pod
    issued_at: float  # monotonic
    deadline: float  # monotonic
    direction: str = ""  # shrink | grow | dead-slice


class IncrementalDemandView:
    """Delta-maintained mirror of the admitter's scheduling state.

    Primed once from a full ``gang_snapshots()`` pass, then kept current
    by draining ``admitter.demand_changes()`` — a refresh costs O(changed
    gangs), not O(fleet), which is what keeps a scheduler tick flat at
    10k jobs (docs/control_plane_scale.md). Pool-membership changes
    (set_pool, slice death, drain completion of a dead slice) arrive as
    ``pool_changed`` and force a full rebuild, because slice shapes feed
    the total-chip denominator.

    The full-rescan path (``_rebuild``) doubles as the parity oracle:
    ``parity_diff()`` recomputes from scratch and reports any divergence;
    tests drive it over randomized event streams.

    Not thread-safe on its own — the scheduler calls ``refresh()`` only
    from its tick loop, matching the admitter's single-consumer contract
    for ``demand_changes()``.
    """

    def __init__(self, admitter) -> None:
        self.admitter = admitter
        self._snaps: Dict[str, GangSnapshot] = {}
        self._usage: Dict[str, int] = {}
        self._total = 0
        self._rev = -1
        self._primed = False
        self.rebuilds_total = 0
        self.delta_refreshes_total = 0

    def refresh(self) -> int:
        """Fold pending admitter deltas into the mirror; returns the
        admitter rev now covered. Call before reading snapshots/usage."""
        if not self._primed:
            return self._rebuild()
        rev, delta, pool_changed = self.admitter.demand_changes(self._rev)
        if pool_changed:
            return self._rebuild()
        for key, snap in delta.items():
            old = self._snaps.get(key)
            if old is not None and old.reserved_chips:
                left = self._usage.get(old.tenant, 0) - old.reserved_chips
                if left > 0:
                    self._usage[old.tenant] = left
                else:
                    self._usage.pop(old.tenant, None)
            if snap is None:
                self._snaps.pop(key, None)
            else:
                self._snaps[key] = snap
                if snap.reserved_chips:
                    self._usage[snap.tenant] = (
                        self._usage.get(snap.tenant, 0) + snap.reserved_chips)
        if delta:
            self.delta_refreshes_total += 1
        self._rev = rev
        return rev

    def _rebuild(self) -> int:
        # Drain stale marks FIRST: anything marked after this drain stays
        # marked for the next refresh; a change landing between the drain
        # and the snapshot below is both in the snapshot and re-applied
        # as a (idempotent) delta next refresh.
        rev, _, _ = self.admitter.demand_changes(-1)
        snaps = self.admitter.gang_snapshots()
        self._snaps = {g.key: g for g in snaps}
        usage: Dict[str, int] = {}
        for g in snaps:
            if g.reserved_chips:
                usage[g.tenant] = usage.get(g.tenant, 0) + g.reserved_chips
        self._usage = usage
        self._total = self.admitter.total_chips()
        self._rev = rev
        self._primed = True
        self.rebuilds_total += 1
        return rev

    # -- readers (valid until the next refresh) --------------------------

    def snapshots(self) -> List[GangSnapshot]:
        return list(self._snaps.values())

    def mirror(self) -> Dict[str, GangSnapshot]:
        return dict(self._snaps)

    def usage(self) -> Dict[str, int]:
        return dict(self._usage)

    def total_chips(self) -> int:
        return self._total

    # -- parity oracle ---------------------------------------------------

    def parity_diff(self) -> Dict:
        """Recompute demand from scratch and diff the mirror against it.
        Empty dict = parity. Only meaningful when the admitter is quiet
        (tests); a concurrent mutation between the two reads is not a
        divergence."""
        oracle = {g.key: g for g in self.admitter.gang_snapshots()}
        usage: Dict[str, int] = {}
        for g in oracle.values():
            if g.reserved_chips:
                usage[g.tenant] = usage.get(g.tenant, 0) + g.reserved_chips
        diff: Dict = {}
        for key in set(oracle) | set(self._snaps):
            if oracle.get(key) != self._snaps.get(key):
                diff[key] = {"oracle": oracle.get(key),
                             "view": self._snaps.get(key)}
        if usage != self._usage:
            diff["__usage__"] = {"oracle": usage, "view": dict(self._usage)}
        total = self.admitter.total_chips()
        if total != self._total:
            diff["__total__"] = {"oracle": total, "view": self._total}
        return diff


class CapacityScheduler(CapacityDirector):
    """Implements the admitter's CapacityDirector hooks (policy order,
    caps, slice pricing) and drives preemption/elastic passes on tick()."""

    def __init__(
        self,
        admitter,
        store,
        config: Optional[CapacityConfig] = None,
    ) -> None:
        self.admitter = admitter
        self.store = store
        self.config = config or CapacityConfig()
        self.quotas = TenantQuotas(
            weights=self.config.tenant_weights, caps=self.config.tenant_caps
        )
        self.policy = make_policy(self.config.policy, self.quotas)
        self._lock = new_lock("sched.capacity.CapacityScheduler._lock")
        self._last_tick: Optional[float] = None
        self._preemptions_total = 0
        self._resizes_total = 0
        # O(changed) tick plumbing: the delta-maintained demand mirror
        # (None = full-rescan fallback), the admitter rev the last full
        # pass round covered, and the earliest future moment a pure time
        # gate (grow_delay) could newly open with nothing else changing
        self._view = (
            IncrementalDemandView(admitter)
            if (self.config.incremental_demand_view
                and hasattr(admitter, "demand_changes"))
            else None
        )
        self._sched_rev = -1
        self._next_due = 0.0
        self._ticks_total = 0
        self._ticks_skipped = 0
        # live-reshard plane: control channel into running pods (the
        # operator wires the executor's post_control on the local
        # executor, or a transport/control.SocketControlRouter.post over
        # the socket plane in kube mode; None = no channel, every resize
        # takes the checkpoint path), pending RESIZEs, and the
        # kubedl_reshards_total / resize-downtime series
        self._control: Optional[Callable[[str, str, Dict], Optional[str]]] = None
        self._pending_reshards: Dict[str, _PendingReshard] = {}
        self._reshards_total = {"ok": 0, "staged": 0, "fallback": 0,
                                "failed": 0}
        self._downtime_counts = [0] * (len(RESHARD_BUCKETS) + 1)
        self._downtime_sum = 0.0
        self._downtime_n = 0
        self._downtime_last = 0.0
        # flight recorder (obs/trace.py Tracer), wired by the operator:
        # preemptions and RESIZE-ladder outcomes become spans on the
        # victim/target gang's timeline
        self.tracer = None
        if hasattr(admitter, "drain_timeout"):
            admitter.drain_timeout = self.config.drain_timeout
        admitter.set_director(self)

    # ------------------------------------------------------------------
    # CapacityDirector hooks — called UNDER the admitter's lock; they
    # delegate straight to the policy (which only takes leaf locks).
    # ------------------------------------------------------------------

    def order_waiting(self, waiting, usage, total_chips):
        return self.policy.order_waiting(waiting, usage, total_chips)

    def may_reserve(self, gang, usage, total_chips):
        return self.policy.may_reserve(gang, usage, total_chips)

    def choose_slices(self, gang, candidates, n):
        return self.policy.choose_slices(gang, candidates, n)

    def chips_headroom(self, gang, usage, total_chips):
        cap = self.quotas.cap(gang.tenant)
        if cap is None:
            return None
        return max(cap - usage.get(gang.tenant, 0), 0)

    # ------------------------------------------------------------------
    # the scheduling loop
    # ------------------------------------------------------------------

    def tick(self) -> None:
        """One scheduling round: accrue usage, grant what's grantable,
        then unblock the queue with preemption / elastic resizes.

        With the incremental view, the preempt/elastic round is SKIPPED
        when it provably reproduces a no-op: the admitter rev is
        unchanged since the last full round (so every demand_view probe
        and may_reserve gate would answer the same), no gang is waiting
        for slices (the preempt pass and the shrink arm both early-out),
        no RESIZE is pending, and no grow_delay gate has newly opened.
        Policy ordering IS time-sensitive (fair-share deficits accrue),
        but ordering only matters when something is waiting — which
        forces a full round. Accrual itself runs every tick."""
        now = time.monotonic()
        if self._view is not None:
            rev = self._view.refresh()
            usage, total = self._view.usage(), self._view.total_chips()
            snaps = self._view.snapshots()
        else:
            rev = -1
            snaps = self.admitter.gang_snapshots()
            usage, total = self._usage(snaps)
        with self._lock:
            self._ticks_total += 1
            if self._last_tick is not None:
                self.quotas.accrue(usage, now - self._last_tick)
            self._last_tick = now
            pending = bool(self._pending_reshards)
        if (
            self._view is not None
            and rev == self._sched_rev
            and not pending
            and now < self._next_due
            and not any(
                not g.slice_names and g.tpu_chips > 0 for g in snaps)
            # drains expire on wall-clock deadlines with no rev bump —
            # kick()'s sweep must keep running while any are in flight
            and not (hasattr(self.admitter, "draining")
                     and self.admitter.draining())
        ):
            with self._lock:
                self._ticks_skipped += 1
            return
        self.admitter.kick()
        if self._view is None:
            # full-rescan fallback: each pass snapshots for itself, the
            # pre-incremental behavior
            self._reshard_pass()
            if self.config.enable_preemption:
                self._preempt_pass()
            if self.config.enable_elastic:
                self._elastic_pass()
            self.admitter.kick()
            return
        # each pass works on a view refreshed past the previous pass's
        # mutations (kick's grants, preemption's evictions) — every
        # refresh is O(changed), so this costs deltas, not rescans
        self._view.refresh()
        self._reshard_pass()
        if self.config.enable_preemption:
            self._preempt_pass(self._view.snapshots(), self._view.usage(),
                               self._view.total_chips())
        if self.config.enable_elastic:
            self._view.refresh()
            self._elastic_pass(self._view.snapshots(), self._view.usage(),
                               self._view.total_chips())
        self.admitter.kick()
        # rev AFTER the round: the round's own mutations don't force a
        # re-round (their follow-on effects — drain confirms, pod exits,
        # re-grants — all bump the rev when they land). Taken from a
        # refresh() so the recorded rev covers exactly the deltas folded
        # into the mirror — a mutation racing this line lands at a higher
        # rev and defeats the next tick's skip.
        self._sched_rev = self._view.refresh()
        self._next_due = self._next_time_gate(time.monotonic())

    def _next_time_gate(self, now: float) -> float:
        """Earliest future moment the elastic grow gate could newly open
        with NO admitter event in between. Waiting/held gangs never reach
        this: their presence disables the skip entirely (holds, shrink
        delays, and policy-order accrual all resolve through full
        rounds). float('inf') = nothing time-gated; skip until a rev
        bump."""
        due = float("inf")
        if self._view is None or not self.config.enable_elastic:
            return due
        for g in self._view.snapshots():
            if (
                g.slice_names
                and g.tpu_chips > 0
                and len(g.admissible_slices) >= 2
                and g.requested_slice in g.admissible_slices
                and g.admissible_slices.index(g.requested_slice) > 0
            ):
                gate = g.granted_at + self.config.grow_delay
                if gate > now:
                    due = min(due, gate)
        return due

    # -- live reshard ----------------------------------------------------

    def attach_control(self, post_fn) -> None:
        """Wire the pod control channel: post_fn(namespace, pod_name,
        message) -> reply path or None. Backends: executor.post_control
        (local executor, files in the pod's control dir) or
        transport/control.SocketControlRouter.post (kube mode — the
        message rides the socket plane and the reply is spooled to a
        local file, so this polling loop is transport-blind). Without
        one, every resize falls back to checkpoint-then-evict."""
        with self._lock:
            self._control = post_fn

    def _gang_pods(self, gang: GangSnapshot) -> List:
        """The gang's live pods (shared kind-guarded selection —
        gang/interface.py gang_pods)."""
        return gang_pods(self.store, gang.key, gang.kind)

    def _post_resize(self, gang: GangSnapshot, direction: str) -> bool:
        """Post RESIZE to every pod of the gang; returns False (caller
        takes the checkpoint path) when there is no control channel, no
        pods, a pod refuses the message, or a RESIZE is already pending
        for the gang. The new shape is the gang's CURRENT requested_slice
        (the resize directive retargeted it first)."""
        with self._lock:
            control = self._control
            if control is None or gang.key in self._pending_reshards:
                return False
        try:
            from kubedl_tpu.executor.tpu_topology import parse_slice_type

            chips = parse_slice_type(gang.requested_slice).chips
        except ValueError:
            return False
        pods = self._gang_pods(gang)
        if not pods:
            return False
        # the job's own quiesce budget (spec.elastic.quiesceTimeoutS,
        # riding the gang snapshot) widens both the message and the reply
        # deadline — worker 0 may legitimately wait that long at the
        # staging barrier, and a deadline shorter than the budget would
        # tear down gangs mid-stage
        quiesce = max(self.config.quiesce_timeout,
                      float(getattr(gang, "quiesce_s", 0.0)))
        msg = {
            "type": "RESIZE",
            "chips": chips,
            "slice": gang.requested_slice,
            "quiesce_timeout_s": quiesce,
        }
        replies = []
        for pod in pods:
            path = control(pod.metadata.namespace, pod.metadata.name, dict(msg))
            if path is None:
                # a pod we cannot reach must not half-resize the gang:
                # abandon the live path entirely (fallback closed); pods
                # already messaged will quiesce, find one peer missing at
                # the staging barrier (multi-pod) or complete harmlessly
                # (single-pod in-process, re-resized by the fallback)
                return False
            replies.append(path)
        now = time.monotonic()
        wait = self.config.reshard_reply_timeout + quiesce
        with self._lock:
            self._pending_reshards[gang.key] = _PendingReshard(
                gang_key=gang.key,
                replies=replies,
                issued_at=now,
                deadline=now + wait,
                direction=direction,
            )
        log.info("live reshard (%s): gang %s -> %s (%d pods)",
                 direction, gang.key, gang.requested_slice, len(pods))
        return True

    def _reshard_pass(self) -> None:
        """Poll pending RESIZE replies. All-ok completes the reshard
        (downtime observed, the old slices' drain confirmed); any
        fallback/failed reply — or the deadline — fails CLOSED into the
        checkpoint path: the gang's pods are deleted and re-admitted
        through Orbax restore. Reply files are written atomically by the
        trainer, so a parsed reply is always complete."""
        with self._lock:
            pending = list(self._pending_reshards.values())
        now = time.monotonic()
        for p in pending:
            results = []
            for path in p.replies:
                try:
                    with open(path) as f:
                        results.append(json.load(f))
                except (OSError, ValueError):
                    results.append(None)
            ready = [r for r in results if r is not None]
            bad = [r for r in ready
                   if r.get("outcome") not in ("ok", "staged")]
            if bad:
                self._finish_reshard(p, "fallback",
                                     reason=bad[0].get("error", "pod fell back"))
            elif len(ready) == len(p.replies):
                if any(r.get("outcome") == "staged" for r in ready):
                    # staged lane: the pods exited to reassemble on the new
                    # topology — NOT yet provably resharded (reassembly can
                    # still fall back to checkpoint restore), so no "ok",
                    # no downtime, and no early drain confirm: the pod
                    # exits themselves confirm the drain via release()
                    self._finish_reshard(p, "staged")
                else:
                    downtimes = [float(r.get("downtime_s", 0.0))
                                 for r in ready]
                    self._finish_reshard(
                        p, "ok",
                        downtime=max(downtimes) if downtimes else None)
            elif p.deadline <= now:
                self._finish_reshard(
                    p, "failed",
                    reason=f"{len(p.replies) - len(ready)} pod replies "
                           f"missing {now - p.issued_at:.0f}s after issue")

    def _finish_reshard(
        self,
        p: _PendingReshard,
        outcome: str,
        downtime: Optional[float] = None,
        reason: str = "",
    ) -> None:
        with self._lock:
            self._pending_reshards.pop(p.gang_key, None)
            self._reshards_total[outcome] = (
                self._reshards_total.get(outcome, 0) + 1)
            if downtime is not None:
                self._downtime_last = downtime
                self._downtime_sum += downtime
                self._downtime_n += 1
                for i, b in enumerate(RESHARD_BUCKETS):
                    if downtime <= b:
                        self._downtime_counts[i] += 1
                        break
                else:
                    self._downtime_counts[-1] += 1
        # the ladder rung as a span: issue -> resolution, outcome attr
        # (the trainer's reshard.live/staged/fallback spans are the
        # compute-plane half of the same story)
        self._record_span(
            p.gang_key, "sched.reshard",
            duration_s=max(time.monotonic() - p.issued_at, 0.0),
            direction=p.direction, outcome=outcome,
            **({"downtime_s": round(downtime, 4)} if downtime is not None
               else {}),
            **({"reason": str(reason)[:200]} if reason else {}),
        )
        namespace, _, name = p.gang_key.partition("/")
        if outcome == "ok":
            log.info("live reshard (%s) of gang %s complete: downtime %.3fs",
                     p.direction, p.gang_key, downtime or 0.0)
            # the gang provably runs on the new shape: its OLD slices'
            # drain can finish now (no pod exits will ever confirm it)
            if hasattr(self.admitter, "confirm_drain"):
                self.admitter.confirm_drain(p.gang_key)
            return
        if outcome == "staged":
            log.info("live reshard (%s) of gang %s staged: pods restart "
                     "onto the new topology (reassembly falls back closed "
                     "to checkpoint restore if invalid)",
                     p.direction, p.gang_key)
            return
        log.warning("live reshard (%s) of gang %s %s (%s); falling back "
                    "closed to checkpoint-then-evict",
                    p.direction, p.gang_key, outcome, reason)
        # fallback CLOSED: delete the pods — each saved (or kept) its last
        # durable checkpoint; the engine recreates them Pending and the
        # gang re-admits through checkpoint restore, never through a
        # half-resharded state
        snaps = {g.key: g for g in self.admitter.gang_snapshots()}
        g = snaps.get(p.gang_key)
        if g is not None:
            self._delete_gang_pods(g)

    def slice_failed(self, slice_name: str) -> None:
        """Executor/inventory report: a slice died mid-run. The admitter
        parks the dead slice in the drain accounting (chips release once)
        and un-reserves the owning gang; a live-reshard gang is offered a
        shrink to a declared fallback shape at the step it quiesces —
        fault tolerance as cheap shrink — and only failing that does the
        whole gang take the checkpoint-evict path."""
        if not hasattr(self.admitter, "slice_failed"):
            return
        gang_key = self.admitter.slice_failed(slice_name)
        if gang_key is None:
            return
        snaps = {g.key: g for g in self.admitter.gang_snapshots()}
        g = snaps.get(gang_key)
        if g is None:
            return
        if g.slice_names:
            # the reservation pass already re-granted the SAME shape on
            # surviving hardware; pods keep running (local executor) —
            # nothing to reshard
            log.info("gang %s re-granted %s after slice %s died",
                     gang_key, g.slice_names, slice_name)
            return
        if g.live_reshard and g.requested_slice in g.admissible_slices:
            rank = g.admissible_slices.index(g.requested_slice)
            for alt in g.admissible_slices[rank + 1:]:
                if not self.admitter.resize_gang(g.namespace, g.name, alt):
                    continue
                fresh = {s.key: s for s in self.admitter.gang_snapshots()}
                g2 = fresh.get(gang_key)
                if g2 is not None and g2.slice_names:
                    self._resized(g, alt, "dead-slice shrink")
                    if self._post_resize(g2, "dead-slice"):
                        return
                    break  # retargeted+reserved but unreachable pods
                # retargeted but nothing free at this shape: keep walking
                # the ladder from the new current shape
                g = g2 if g2 is not None else g
        log.warning("gang %s lost slice %s with no live-reshard path; "
                    "taking the checkpoint-evict path", gang_key, slice_name)
        if g.live_reshard:
            # the gang opted in but no fallback shape was attainable /
            # reachable: that IS a reshard fallback for the metric
            with self._lock:
                self._reshards_total["fallback"] += 1
            self._record_span(
                gang_key, "sched.reshard", direction="dead-slice",
                outcome="fallback", reason=f"slice {slice_name} died with "
                                           f"no attainable fallback shape")
        self._delete_gang_pods(g)

    def _record_span(self, gang_key: str, name: str,
                     duration_s: float = 0.0, **attrs) -> None:
        """Record one flight-recorder span on a gang's timeline (no-op
        without a tracer; recording must never block scheduling)."""
        if self.tracer is None:
            return
        from kubedl_tpu.obs.trace import trace_id_for

        namespace, _, job = gang_key.partition("/")
        try:
            self.tracer.record(
                name, duration_s=duration_s,
                trace_id=trace_id_for(namespace, job),
                job=job, namespace=namespace, **attrs)
        except Exception:  # noqa: BLE001 — recording must never block scheduling
            pass

    def _usage(self, snaps: Optional[List[GangSnapshot]] = None):
        """(tenant -> reserved chips, total pool chips). Pass `snaps`
        when a gang_snapshots() list is already in hand — each snapshot
        pass takes the admitter lock, so don't take it twice."""
        if snaps is None:
            snaps = self.admitter.gang_snapshots()
        usage: Dict[str, int] = {}
        for g in snaps:
            if g.reserved_chips:
                usage[g.tenant] = usage.get(g.tenant, 0) + g.reserved_chips
        return usage, self.admitter.total_chips()

    def _waiting(self, snaps: List[GangSnapshot], now: float) -> List[GangSnapshot]:
        return [
            g for g in snaps
            if not g.slice_names and g.tpu_chips > 0 and g.hold_until <= now
        ]

    # -- preemption ------------------------------------------------------

    def _preempt_pass(
        self,
        snaps: Optional[List[GangSnapshot]] = None,
        usage: Optional[Dict[str, int]] = None,
        total: Optional[int] = None,
    ) -> None:
        """Evict policy-selected victims for the first unsatisfiable
        waiting gang the policy favors. One demander per tick: each
        eviction changes the pool, so re-evaluate from fresh state."""
        now = time.monotonic()
        if snaps is None:
            snaps = self.admitter.gang_snapshots()
        waiting = self._waiting(snaps, now)
        if not waiting:
            return
        if usage is None or total is None:
            usage, total = self._usage(snaps)
        for demander in self.policy.order_waiting(waiting, usage, total):
            if not self.policy.may_reserve(demander, usage, total):
                continue
            view = self.admitter.demand_view(demander.namespace, demander.name)
            if view is None:
                continue
            # draining slices are capacity already committed to free (a
            # previous eviction's victims are still checkpointing) —
            # evicting MORE victims on top would be an eviction storm
            # against latency the drain phase exists to absorb
            draining = view.get("draining", 0)
            shortfall = view["needed"] - view["free"] - draining
            if shortfall <= 0:
                continue  # kick() / drain completion will grant it
            holders = [h for h, _ in view["holders"]]
            matching = {h.key: m for h, m in view["holders"]}
            victims = self.policy.select_victims(demander, holders, usage, total)
            if not victims:
                continue
            # Feasibility bound: evicting must actually unblock the
            # demander. A demand the policy's victims + free slices can
            # never cover (e.g. numSlices beyond the pool) would
            # otherwise trigger a perpetual checkpoint-evict storm that
            # starves every victim without ever admitting the demander.
            coverable = view["free"] + draining + sum(
                matching.get(v.key, 0) for v in victims
            )
            if coverable < view["needed"]:
                continue
            freed = 0
            for victim in victims:
                if freed >= shortfall:
                    break
                hold = min(
                    self.config.preemption_backoff * (2 ** min(victim.preemptions, 6)),
                    self.config.preemption_max_backoff,
                )
                released = self.admitter.evict_gang(
                    victim.namespace, victim.name, hold_seconds=hold
                )
                if not released:
                    continue
                freed += matching.get(victim.key, len(released))
                self._preempted(victim, demander, released, hold)
            if freed:
                return  # pool changed; next tick re-evaluates

    def _preempted(self, victim: GangSnapshot, demander: GangSnapshot,
                   released: List[str], hold: float) -> None:
        with self._lock:
            self._preemptions_total += 1
        self.quotas.note_preemption(victim.tenant)
        self._record_span(
            victim.key, "sched.preempt",
            demander=demander.key, slices=list(released),
            hold_s=round(hold, 3), tenant=victim.tenant)
        log.info(
            "preempted gang %s (tenant=%s prio=%d, slices %s) for %s "
            "(tenant=%s prio=%d); requeued with %.1fs backoff",
            victim.key, victim.tenant, victim.priority, released,
            demander.key, demander.tenant, demander.priority, hold,
        )
        self._delete_gang_pods(victim)

    def _delete_gang_pods(self, gang: GangSnapshot) -> None:
        """Checkpoint-then-evict: deleting the pods SIGTERMs the trainer
        (it saves a checkpoint and exits); the engine recreates them
        Pending until the gang is re-admitted.

        The victim's slices are NOT re-grantable yet: evict_gang parked
        them in the drain phase, and they free only when the executor
        confirms each pod's processes exited (release() fires after the
        SIGTERM-grace kill completes) or the drain deadline passes — so
        a successor's pods can never start on a slice whose previous
        owner is still checkpointing."""
        for pod in self._gang_pods(gang):
            try:
                self.store.delete("Pod", pod.metadata.namespace, pod.metadata.name)
            except NotFound:
                pass

    # -- elastic resize --------------------------------------------------

    def _elastic_pass(
        self,
        snaps: Optional[List[GangSnapshot]] = None,
        usage: Optional[Dict[str, int]] = None,
        total: Optional[int] = None,
    ) -> None:
        now = time.monotonic()
        if snaps is None:
            snaps = self.admitter.gang_snapshots()
        if usage is None or total is None:
            usage, total = self._usage(snaps)
        for g in snaps:
            if len(g.admissible_slices) < 2 or g.tpu_chips <= 0:
                continue
            if g.requested_slice not in g.admissible_slices:
                continue
            rank = g.admissible_slices.index(g.requested_slice)
            if not g.slice_names:
                self._maybe_shrink(g, rank, now, usage, total)
            else:
                self._maybe_grow(g, rank, now, usage, total)

    def _maybe_shrink(
        self, g: GangSnapshot, rank: int, now: float,
        usage: Dict[str, int], total: int,
    ) -> None:
        """A waiting gang whose current shape stays unattainable — no
        free matching slice, OR its tenant cap can't fit that shape —
        falls to the first declared fallback that is both free and
        cap-admissible right now. Holds don't block the re-target (the
        backoff still paces the re-admission)."""
        if now - g.waiting_since < self.config.shrink_delay:
            return
        # shield-aware probes: shrinking toward a slice the reservation
        # pass would refuse (held back for an earlier waiting gang) is a
        # needless permanent downgrade
        view = self.admitter.demand_view(
            g.namespace, g.name, respect_shields=True)
        if view is None:
            return
        attainable = (
            view["free"] >= view["needed"]
            and self.policy.may_reserve(g, usage, total)
        )
        if attainable:
            return
        for alt in g.admissible_slices[rank + 1:]:
            probe = self.admitter.demand_view(
                g.namespace, g.name, slice_type=alt, respect_shields=True)
            if (
                probe is not None
                and probe["free"] >= probe["needed"]
                and self.policy.may_reserve(
                    replace(g, requested_slice=alt), usage, total
                )
            ):
                if self.admitter.resize_gang(g.namespace, g.name, alt):
                    self._resized(g, alt, "shrink")
                return

    def _maybe_grow(
        self, g: GangSnapshot, rank: int, now: float,
        usage: Dict[str, int], total: int,
    ) -> None:
        """A gang running below its preferred shape grows back through
        checkpoint-evict-readmit once a better declared shape is free
        and it has run long enough to bank progress. Growing EVICTS a
        running gang (its own), so --disable-preemption turns it off —
        that flag promises the scheduler never evicts running gangs."""
        if not self.config.enable_preemption:
            return
        if rank == 0 or now - g.granted_at < self.config.grow_delay:
            return
        for better in g.admissible_slices[:rank]:
            probe = self.admitter.demand_view(
                g.namespace, g.name, slice_type=better, respect_shields=True)
            if probe is None or probe["free"] < probe["needed"]:
                continue
            # the grown reservation must still fit the tenant cap; the
            # gang's own chips come back when its current slices release
            adj = dict(usage)
            adj[g.tenant] = max(0, adj.get(g.tenant, 0) - g.reserved_chips)
            if not self.policy.may_reserve(
                replace(g, requested_slice=better), adj, total
            ):
                continue
            released = self.admitter.evict_gang(
                g.namespace, g.name, hold_seconds=0.0, resize_to=better
            )
            if released:
                self._resized(g, better, "grow")
                if g.live_reshard:
                    # live grow: the pods reshard onto the pre-granted new
                    # slices in place; the OLD slices stay draining until
                    # the replies confirm (then confirm_drain frees them)
                    # — any failure falls back closed via _reshard_pass
                    fresh = {s.key: s for s in self.admitter.gang_snapshots()}
                    g2 = fresh.get(g.key)
                    if g2 is not None and self._post_resize(g2, "grow"):
                        return
                self._delete_gang_pods(g)
            return

    def _resized(self, g: GangSnapshot, shape: str, direction: str) -> None:
        with self._lock:
            self._resizes_total += 1
        log.info(
            "elastic %s: gang %s re-targeted %s -> %s (declared shapes: %s)",
            direction, g.key, g.requested_slice, shape, g.admissible_slices,
        )

    # ------------------------------------------------------------------
    # exposition (metrics/runtime_metrics.py register_capacity, CLI)
    # ------------------------------------------------------------------

    def version(self):
        """Cheap change token for the metrics render cache: moves when
        anything the prom exposition derives from may have moved —
        admitter scheduling state (usage, tenants), the scheduler's own
        counters, or quota accrual. snapshot() is O(fleet) (it lists the
        whole queue); this is O(1) and lets an unchanged scrape skip it
        entirely (docs/control_plane_scale.md)."""
        if not hasattr(self.admitter, "demand_rev"):
            return None  # no change feed — the scrape renders live
        rev = self.admitter.demand_rev()
        with self._lock:
            return (
                rev,
                self._preemptions_total,
                self._resizes_total,
                tuple(sorted(self._reshards_total.items())),
                self._downtime_n,
                len(self._pending_reshards),
                self.quotas.version(),
            )

    def snapshot(self) -> Dict:
        now = time.monotonic()
        snaps = self.admitter.gang_snapshots()
        usage, total = self._usage(snaps)
        # same active set the fair-share policy scores with (TPU demand
        # or usage) — CPU-only tenants must not dilute the displayed
        # shares into numbers the scheduler never enforces
        active = {g.tenant for g in snaps if g.tpu_chips > 0} | set(usage)
        draining = (
            self.admitter.draining()
            if hasattr(self.admitter, "draining") else {}
        )
        queue = []
        for g in sorted(snaps, key=lambda s: (-s.priority, s.seq)):
            if g.slice_names:
                state = "Reserved"
            elif g.hold_until > now:
                state = "Held"
            elif g.tpu_chips > 0:
                state = "Waiting"
            else:
                state = "CPU"
            queue.append({
                "gang": g.key,
                "kind": g.kind,
                "tenant": g.tenant,
                "priority": g.priority,
                "shape": g.requested_slice or f"{g.tpu_chips} chips",
                "admissible": list(g.admissible_slices),
                "state": state,
                "slices": list(g.slice_names),
                # the gang's PREVIOUS slices still draining post-evict
                # (held back until its pods confirm exit)
                "draining": draining.get(g.key, []),
                "chips": g.reserved_chips,
                "preemptions": g.preemptions,
                "waiting_seconds": (
                    round(now - g.waiting_since, 3)
                    if not g.slice_names and g.waiting_since else 0.0
                ),
            })
        with self._lock:
            preemptions = self._preemptions_total
            resizes = self._resizes_total
            ticks = self._ticks_total
            skipped = self._ticks_skipped
            reshards = dict(self._reshards_total)
            downtime = {
                "last": self._downtime_last,
                "sum": self._downtime_sum,
                "count": self._downtime_n,
                "buckets": list(zip(RESHARD_BUCKETS, self._downtime_counts)),
                "overflow": self._downtime_counts[-1],
            }
            pending = len(self._pending_reshards)
        return {
            "policy": self.policy.name,
            "total_chips": total,
            "tenants": self.quotas.snapshot(usage, total, active),
            "queue": queue,
            "preemptions_total": preemptions,
            "resizes_total": resizes,
            "ticks_total": ticks,
            "ticks_skipped": skipped,
            "demand_view": (
                {
                    "rebuilds_total": self._view.rebuilds_total,
                    "delta_refreshes_total": self._view.delta_refreshes_total,
                }
                if self._view is not None else None
            ),
            "reshards_total": reshards,
            "reshards_pending": pending,
            "resize_downtime": downtime,
        }
