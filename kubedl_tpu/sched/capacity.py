"""The capacity scheduler — fair-share admission, active preemption,
elastic slice resizing (docs/scheduling.md).

Sits between the reconciler engine and the gang admitter: the admitter
executes reserve/evict/resize directives; this scheduler decides them on a
periodic tick (wired as a manager loop, core/manager.py add_loop). Three
pillars:

  * tenant fair-share — per-tenant weights/caps (sched/quota.py) drive the
    waiting-queue order and admission gates through the pluggable policy
    (sched/policy.py: fifo | priority | fair_share | gavel);
  * active preemption — when a policy-favored gang waits on a full pool,
    victims are selected by policy and driven through the existing
    checkpoint-then-evict path: the admitter releases their slices with a
    requeue backoff, then the victims' pods are DELETED — the local
    executor SIGTERMs the trainer, which saves an Orbax checkpoint
    (train/trainer.py); the engine recreates the pods, which sit Pending
    until re-admission, where the trainer restores (the machinery
    test_preemption_resume.py exercises);
  * elastic resize — a job declaring admissible fallback shapes
    (SchedulingPolicy.tpu_slice_fallbacks) is re-targeted at a smaller
    shape when its preferred one stays unavailable (Tenplex-style
    shape-agnostic restore in the trainer), and grown back when capacity
    frees up.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from kubedl_tpu.core.store import NotFound
from kubedl_tpu.gang.interface import (
    ANNOTATION_GANG_NAME,
    CapacityDirector,
    GangSnapshot,
)
from kubedl_tpu.sched.policy import make_policy
from kubedl_tpu.sched.quota import TenantQuotas

log = logging.getLogger("kubedl_tpu.sched")


@dataclass
class CapacityConfig:
    policy: str = "priority"  # fifo | priority | fair_share | gavel
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    tenant_caps: Dict[str, int] = field(default_factory=dict)
    enable_preemption: bool = True
    # victim requeue pacing: hold = backoff * 2^min(preemptions, 6), capped
    preemption_backoff: float = 0.5
    preemption_max_backoff: float = 30.0
    enable_elastic: bool = True
    # how long a gang waits at an unavailable shape before shrinking to a
    # declared fallback, and how long it runs degraded before growing back
    shrink_delay: float = 0.5
    grow_delay: float = 2.0
    # eviction drain safety valve: evicted slices free at the latest this
    # many seconds after the eviction if pod-exit confirmations never
    # arrive (real-kubelet mode); the local executor confirms in ~the
    # SIGTERM grace. Must exceed the executor's grace window.
    drain_timeout: float = 30.0


class CapacityScheduler(CapacityDirector):
    """Implements the admitter's CapacityDirector hooks (policy order,
    caps, slice pricing) and drives preemption/elastic passes on tick()."""

    def __init__(
        self,
        admitter,
        store,
        config: Optional[CapacityConfig] = None,
    ) -> None:
        self.admitter = admitter
        self.store = store
        self.config = config or CapacityConfig()
        self.quotas = TenantQuotas(
            weights=self.config.tenant_weights, caps=self.config.tenant_caps
        )
        self.policy = make_policy(self.config.policy, self.quotas)
        self._lock = threading.Lock()
        self._last_tick: Optional[float] = None
        self._preemptions_total = 0
        self._resizes_total = 0
        if hasattr(admitter, "drain_timeout"):
            admitter.drain_timeout = self.config.drain_timeout
        admitter.set_director(self)

    # ------------------------------------------------------------------
    # CapacityDirector hooks — called UNDER the admitter's lock; they
    # delegate straight to the policy (which only takes leaf locks).
    # ------------------------------------------------------------------

    def order_waiting(self, waiting, usage, total_chips):
        return self.policy.order_waiting(waiting, usage, total_chips)

    def may_reserve(self, gang, usage, total_chips):
        return self.policy.may_reserve(gang, usage, total_chips)

    def choose_slices(self, gang, candidates, n):
        return self.policy.choose_slices(gang, candidates, n)

    def chips_headroom(self, gang, usage, total_chips):
        cap = self.quotas.cap(gang.tenant)
        if cap is None:
            return None
        return max(cap - usage.get(gang.tenant, 0), 0)

    # ------------------------------------------------------------------
    # the scheduling loop
    # ------------------------------------------------------------------

    def tick(self) -> None:
        """One scheduling round: accrue usage, grant what's grantable,
        then unblock the queue with preemption / elastic resizes."""
        now = time.monotonic()
        usage, total = self._usage()
        with self._lock:
            if self._last_tick is not None:
                self.quotas.accrue(usage, now - self._last_tick)
            self._last_tick = now
        self.admitter.kick()
        if self.config.enable_preemption:
            self._preempt_pass()
        if self.config.enable_elastic:
            self._elastic_pass()
        self.admitter.kick()

    def _usage(self, snaps: Optional[List[GangSnapshot]] = None):
        """(tenant -> reserved chips, total pool chips). Pass `snaps`
        when a gang_snapshots() list is already in hand — each snapshot
        pass takes the admitter lock, so don't take it twice."""
        if snaps is None:
            snaps = self.admitter.gang_snapshots()
        usage: Dict[str, int] = {}
        for g in snaps:
            if g.reserved_chips:
                usage[g.tenant] = usage.get(g.tenant, 0) + g.reserved_chips
        return usage, self.admitter.total_chips()

    def _waiting(self, snaps: List[GangSnapshot], now: float) -> List[GangSnapshot]:
        return [
            g for g in snaps
            if not g.slice_names and g.tpu_chips > 0 and g.hold_until <= now
        ]

    # -- preemption ------------------------------------------------------

    def _preempt_pass(self) -> None:
        """Evict policy-selected victims for the first unsatisfiable
        waiting gang the policy favors. One demander per tick: each
        eviction changes the pool, so re-evaluate from fresh state."""
        now = time.monotonic()
        snaps = self.admitter.gang_snapshots()
        waiting = self._waiting(snaps, now)
        if not waiting:
            return
        usage, total = self._usage(snaps)
        for demander in self.policy.order_waiting(waiting, usage, total):
            if not self.policy.may_reserve(demander, usage, total):
                continue
            view = self.admitter.demand_view(demander.namespace, demander.name)
            if view is None:
                continue
            # draining slices are capacity already committed to free (a
            # previous eviction's victims are still checkpointing) —
            # evicting MORE victims on top would be an eviction storm
            # against latency the drain phase exists to absorb
            draining = view.get("draining", 0)
            shortfall = view["needed"] - view["free"] - draining
            if shortfall <= 0:
                continue  # kick() / drain completion will grant it
            holders = [h for h, _ in view["holders"]]
            matching = {h.key: m for h, m in view["holders"]}
            victims = self.policy.select_victims(demander, holders, usage, total)
            if not victims:
                continue
            # Feasibility bound: evicting must actually unblock the
            # demander. A demand the policy's victims + free slices can
            # never cover (e.g. numSlices beyond the pool) would
            # otherwise trigger a perpetual checkpoint-evict storm that
            # starves every victim without ever admitting the demander.
            coverable = view["free"] + draining + sum(
                matching.get(v.key, 0) for v in victims
            )
            if coverable < view["needed"]:
                continue
            freed = 0
            for victim in victims:
                if freed >= shortfall:
                    break
                hold = min(
                    self.config.preemption_backoff * (2 ** min(victim.preemptions, 6)),
                    self.config.preemption_max_backoff,
                )
                released = self.admitter.evict_gang(
                    victim.namespace, victim.name, hold_seconds=hold
                )
                if not released:
                    continue
                freed += matching.get(victim.key, len(released))
                self._preempted(victim, demander, released, hold)
            if freed:
                return  # pool changed; next tick re-evaluates

    def _preempted(self, victim: GangSnapshot, demander: GangSnapshot,
                   released: List[str], hold: float) -> None:
        with self._lock:
            self._preemptions_total += 1
        self.quotas.note_preemption(victim.tenant)
        log.info(
            "preempted gang %s (tenant=%s prio=%d, slices %s) for %s "
            "(tenant=%s prio=%d); requeued with %.1fs backoff",
            victim.key, victim.tenant, victim.priority, released,
            demander.key, demander.tenant, demander.priority, hold,
        )
        self._delete_gang_pods(victim)

    def _delete_gang_pods(self, gang: GangSnapshot) -> None:
        """Checkpoint-then-evict: deleting the pods SIGTERMs the trainer
        (it saves a checkpoint and exits); the engine recreates them
        Pending until the gang is re-admitted.

        The victim's slices are NOT re-grantable yet: evict_gang parked
        them in the drain phase, and they free only when the executor
        confirms each pod's processes exited (release() fires after the
        SIGTERM-grace kill completes) or the drain deadline passes — so
        a successor's pods can never start on a slice whose previous
        owner is still checkpointing."""
        try:
            pods = self.store.list("Pod", namespace=gang.namespace)
        except Exception:  # noqa: BLE001 — store racing shutdown
            return
        for pod in pods:
            if pod.metadata.annotations.get(ANNOTATION_GANG_NAME) != gang.key:
                continue
            # gang keys are ns/name, so a same-named job of ANOTHER kind
            # carries the identical annotation — verify the owner kind
            # before killing anything (same invariant as delete_gang)
            ref = pod.metadata.controller_ref()
            if gang.kind and (ref is None or ref.kind != gang.kind):
                continue
            try:
                self.store.delete("Pod", pod.metadata.namespace, pod.metadata.name)
            except NotFound:
                pass

    # -- elastic resize --------------------------------------------------

    def _elastic_pass(self) -> None:
        now = time.monotonic()
        snaps = self.admitter.gang_snapshots()
        usage, total = self._usage(snaps)
        for g in snaps:
            if len(g.admissible_slices) < 2 or g.tpu_chips <= 0:
                continue
            if g.requested_slice not in g.admissible_slices:
                continue
            rank = g.admissible_slices.index(g.requested_slice)
            if not g.slice_names:
                self._maybe_shrink(g, rank, now, usage, total)
            else:
                self._maybe_grow(g, rank, now, usage, total)

    def _maybe_shrink(
        self, g: GangSnapshot, rank: int, now: float,
        usage: Dict[str, int], total: int,
    ) -> None:
        """A waiting gang whose current shape stays unattainable — no
        free matching slice, OR its tenant cap can't fit that shape —
        falls to the first declared fallback that is both free and
        cap-admissible right now. Holds don't block the re-target (the
        backoff still paces the re-admission)."""
        if now - g.waiting_since < self.config.shrink_delay:
            return
        # shield-aware probes: shrinking toward a slice the reservation
        # pass would refuse (held back for an earlier waiting gang) is a
        # needless permanent downgrade
        view = self.admitter.demand_view(
            g.namespace, g.name, respect_shields=True)
        if view is None:
            return
        attainable = (
            view["free"] >= view["needed"]
            and self.policy.may_reserve(g, usage, total)
        )
        if attainable:
            return
        for alt in g.admissible_slices[rank + 1:]:
            probe = self.admitter.demand_view(
                g.namespace, g.name, slice_type=alt, respect_shields=True)
            if (
                probe is not None
                and probe["free"] >= probe["needed"]
                and self.policy.may_reserve(
                    replace(g, requested_slice=alt), usage, total
                )
            ):
                if self.admitter.resize_gang(g.namespace, g.name, alt):
                    self._resized(g, alt, "shrink")
                return

    def _maybe_grow(
        self, g: GangSnapshot, rank: int, now: float,
        usage: Dict[str, int], total: int,
    ) -> None:
        """A gang running below its preferred shape grows back through
        checkpoint-evict-readmit once a better declared shape is free
        and it has run long enough to bank progress. Growing EVICTS a
        running gang (its own), so --disable-preemption turns it off —
        that flag promises the scheduler never evicts running gangs."""
        if not self.config.enable_preemption:
            return
        if rank == 0 or now - g.granted_at < self.config.grow_delay:
            return
        for better in g.admissible_slices[:rank]:
            probe = self.admitter.demand_view(
                g.namespace, g.name, slice_type=better, respect_shields=True)
            if probe is None or probe["free"] < probe["needed"]:
                continue
            # the grown reservation must still fit the tenant cap; the
            # gang's own chips come back when its current slices release
            adj = dict(usage)
            adj[g.tenant] = max(0, adj.get(g.tenant, 0) - g.reserved_chips)
            if not self.policy.may_reserve(
                replace(g, requested_slice=better), adj, total
            ):
                continue
            released = self.admitter.evict_gang(
                g.namespace, g.name, hold_seconds=0.0, resize_to=better
            )
            if released:
                self._resized(g, better, "grow")
                self._delete_gang_pods(g)
            return

    def _resized(self, g: GangSnapshot, shape: str, direction: str) -> None:
        with self._lock:
            self._resizes_total += 1
        log.info(
            "elastic %s: gang %s re-targeted %s -> %s (declared shapes: %s)",
            direction, g.key, g.requested_slice, shape, g.admissible_slices,
        )

    # ------------------------------------------------------------------
    # exposition (metrics/runtime_metrics.py register_capacity, CLI)
    # ------------------------------------------------------------------

    def snapshot(self) -> Dict:
        now = time.monotonic()
        snaps = self.admitter.gang_snapshots()
        usage, total = self._usage(snaps)
        # same active set the fair-share policy scores with (TPU demand
        # or usage) — CPU-only tenants must not dilute the displayed
        # shares into numbers the scheduler never enforces
        active = {g.tenant for g in snaps if g.tpu_chips > 0} | set(usage)
        draining = (
            self.admitter.draining()
            if hasattr(self.admitter, "draining") else {}
        )
        queue = []
        for g in sorted(snaps, key=lambda s: (-s.priority, s.seq)):
            if g.slice_names:
                state = "Reserved"
            elif g.hold_until > now:
                state = "Held"
            elif g.tpu_chips > 0:
                state = "Waiting"
            else:
                state = "CPU"
            queue.append({
                "gang": g.key,
                "kind": g.kind,
                "tenant": g.tenant,
                "priority": g.priority,
                "shape": g.requested_slice or f"{g.tpu_chips} chips",
                "admissible": list(g.admissible_slices),
                "state": state,
                "slices": list(g.slice_names),
                # the gang's PREVIOUS slices still draining post-evict
                # (held back until its pods confirm exit)
                "draining": draining.get(g.key, []),
                "chips": g.reserved_chips,
                "preemptions": g.preemptions,
                "waiting_seconds": (
                    round(now - g.waiting_since, 3)
                    if not g.slice_names and g.waiting_since else 0.0
                ),
            })
        with self._lock:
            preemptions = self._preemptions_total
            resizes = self._resizes_total
        return {
            "policy": self.policy.name,
            "total_chips": total,
            "tenants": self.quotas.snapshot(usage, total, active),
            "queue": queue,
            "preemptions_total": preemptions,
            "resizes_total": resizes,
        }
