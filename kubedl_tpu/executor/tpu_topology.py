"""TPU slice topology — types, torus coordinates, ICI-aware ring placement.

Net-new vs the reference (which schedules generic GPU/CPU pods): models Cloud
TPU pod slices so gang admission can be all-or-nothing per slice
(SURVEY.md §2.4 "TPU-slice admission") and context-parallel rings can be laid
out on ICI-adjacent hosts (SURVEY.md §7 step 9).

A slice type like "v5e-16" resolves to a chip grid (e.g. 4x4), a
chips-per-host count, and host coordinates. `ring_order` returns hosts in a
snake walk through the torus so consecutive ranks are ICI neighbors — the
placement the JAXJob controller uses for the context-parallel mesh axis.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# generation -> chips per host
CHIPS_PER_HOST = {"v4": 4, "v5p": 4, "v5e": 8, "v6e": 8}

# default chip-grid topologies per slice size (x, y[, z])
_DEFAULT_TOPOLOGY = {
    ("v5e", 1): (1, 1),
    ("v5e", 4): (2, 2),
    ("v5e", 8): (2, 4),
    ("v5e", 16): (4, 4),
    ("v5e", 32): (4, 8),
    ("v5e", 64): (8, 8),
    ("v5e", 128): (8, 16),
    ("v5e", 256): (16, 16),
    ("v6e", 8): (2, 4),
    ("v6e", 16): (4, 4),
    ("v6e", 32): (4, 8),
    ("v6e", 64): (8, 8),
    ("v6e", 256): (16, 16),
}


def _cube_topology(chips: int) -> Tuple[int, ...]:
    """v4/v5p 3D torus: closest factorization into x<=y<=z with 4-chip hosts."""
    best = None
    for x in range(1, int(round(chips ** (1 / 3))) + 2):
        if chips % x:
            continue
        rest = chips // x
        for y in range(x, int(rest**0.5) + 2):
            if rest % y:
                continue
            z = rest // y
            if z < y:
                continue
            cand = (x, y, z)
            score = z - x  # prefer near-cubes
            if best is None or score < best[0]:
                best = (score, cand)
    return best[1] if best else (1, 1, chips)


@dataclass(frozen=True)
class SliceType:
    generation: str  # "v5e" | "v5p" | "v4" | "v6e"
    chips: int
    topology: Tuple[int, ...]

    @property
    def name(self) -> str:
        return f"{self.generation}-{self.chips}"

    @property
    def chips_per_host(self) -> int:
        return min(CHIPS_PER_HOST[self.generation], self.chips)

    @property
    def num_hosts(self) -> int:
        return max(1, self.chips // self.chips_per_host)

    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.topology)


def parse_slice_type(name: str) -> SliceType:
    """Parse "v5e-8", "v5p-32", "v4-16" into a SliceType."""
    m = re.fullmatch(r"(v\d+[ep]?)-(\d+)", name.strip())
    if not m:
        raise ValueError(f"unrecognized TPU slice type: {name!r}")
    gen, chips = m.group(1), int(m.group(2))
    if gen not in CHIPS_PER_HOST:
        raise ValueError(f"unknown TPU generation {gen!r} in {name!r}")
    if gen in ("v4", "v5p"):
        # v4/v5p slice names count TensorCores; chips = cores / 2.
        chip_count = max(chips // 2, 1)
        topo = _cube_topology(chip_count)
    else:
        chip_count = chips
        topo = _DEFAULT_TOPOLOGY.get((gen, chips)) or _grid_topology(chips)
    return SliceType(generation=gen, chips=chip_count, topology=topo)


def _grid_topology(chips: int) -> Tuple[int, int]:
    x = int(chips**0.5)
    while chips % x:
        x -= 1
    return (x, chips // x)


def host_coords(st: SliceType) -> List[Tuple[int, ...]]:
    """Host coordinates in the host grid (chip grid / host footprint)."""
    if len(st.topology) == 2:
        hx, hy = st.topology
        # v5e hosts are 2x4 chip blocks
        fx, fy = (2, 4) if st.chips_per_host == 8 else (1, st.chips_per_host)
        gx, gy = max(hx // fx, 1), max(hy // fy, 1)
        return [(i, j) for i in range(gx) for j in range(gy)]
    hx, hy, hz = st.topology
    # v4/v5p hosts are 2x2x1 chip blocks
    gx, gy, gz = max(hx // 2, 1), max(hy // 2, 1), hz
    return [(i, j, k) for i in range(gx) for j in range(gy) for k in range(gz)]


def ring_order(coords: List[Tuple[int, ...]]) -> List[int]:
    """Indices of `coords` in a snake walk: consecutive entries are grid
    neighbors, so a ring mapped onto this order rides ICI links.

    Works for 2D and 3D host grids; falls back to lexicographic order for
    degenerate shapes.
    """
    if not coords:
        return []
    dims = len(coords[0])
    index_of = {c: i for i, c in enumerate(coords)}
    order: List[int] = []
    if dims == 2:
        xs = sorted({c[0] for c in coords})
        for xi, x in enumerate(xs):
            col = sorted([c for c in coords if c[0] == x], key=lambda c: c[1])
            if xi % 2:
                col.reverse()
            order.extend(index_of[c] for c in col)
    else:
        xs = sorted({c[0] for c in coords})
        for xi, x in enumerate(xs):
            plane = [c for c in coords if c[0] == x]
            ys = sorted({c[1] for c in plane})
            if xi % 2:
                ys.reverse()
            for yi, y in enumerate(ys):
                row = sorted([c for c in plane if c[1] == y], key=lambda c: c[2])
                if (xi + yi) % 2:
                    row.reverse()
                order.extend(index_of[c] for c in row)
    return order


@dataclass
class Placement:
    """Where a pod landed; env() is merged into its containers' environment."""

    node_name: str = ""
    slice_name: str = ""
    slice_type: str = ""
    topology: str = ""
    worker_id: int = 0
    num_workers: int = 1

    def env(self) -> Dict[str, str]:
        return {
            "TPU_WORKER_ID": str(self.worker_id),
            "TPU_SLICE_NAME": self.slice_name,
            "TPU_SLICE_TYPE": self.slice_type,
            "TPU_TOPOLOGY": self.topology,
            "TPU_NUM_WORKERS": str(self.num_workers),
        }


def pipeline_neighbor_env(
    stage: int,
    num_stages: int,
    prev_addr: str = "",
    next_addr: str = "",
) -> Dict[str, str]:
    """Env wiring for one MPMD pipeline stage: which stage this slice's
    program is, and the coordinator addresses of its ring neighbors —
    stage s streams activations to `next` and activation-gradients back
    to `prev`, so each program only ever dials its two neighbors (the
    DCN topology of the MPMD pipeline paper: a chain, not an all-to-all
    Megascale mesh). Endpoint stages carry an empty addr on the missing
    side. The JAXJob controller fills the addrs from the neighbor stage
    worker-0 services (workloads/jaxjob.py set_cluster_spec)."""
    if not (0 <= stage < num_stages):
        raise ValueError(f"stage {stage} out of range [0, {num_stages})")
    return {
        "KUBEDL_PP_STAGE": str(stage),
        "KUBEDL_PP_STAGES": str(num_stages),
        "KUBEDL_PP_PREV_ADDR": prev_addr if stage > 0 else "",
        "KUBEDL_PP_NEXT_ADDR": next_addr if stage < num_stages - 1 else "",
    }


def rl_fleet_env(
    role: str,
    index: int,
    n_actors: int,
    learner_addr: str = "",
    actor_addrs: str = "",
    weight_fanout: int = 4,
    weight_chunk_bytes: int = 1 << 20,
) -> Dict[str, str]:
    """Env wiring for one RL-fleet pod: its role, which actor it is, and
    the transport addresses of its peers — actors dial ONLY the learner
    (trajectories), the learner dials every actor (weight broadcast); a
    hub-and-spoke, not a mesh (the Sebulba topology: PAPERS.md,
    Podracer). `index` is the pod's worker index; actors occupy
    [0, n_actors), so an actor's KUBEDL_RL_ACTOR_INDEX is its worker
    index and the learner carries -1. The JAXJob controller fills the
    addrs from the peer pods' worker services (workloads/jaxjob.py
    set_cluster_spec); the local executor's DirChannel lane ignores
    them and rides KUBEDL_RL_QUEUE_DIR.

    Fleets past ~2 actors distribute weights over the O(log n)
    broadcast tree instead of n learner dials (docs/weights.md);
    KUBEDL_WEIGHTS_FANOUT and KUBEDL_WEIGHTS_CHUNK_BYTES shape that
    tree and ride into every fleet pod so all nodes agree on it."""
    if role not in ("actor", "learner"):
        raise ValueError(f"RL role must be actor|learner, got {role!r}")
    if role == "actor" and not (0 <= index < n_actors):
        raise ValueError(
            f"actor index {index} out of range [0, {n_actors})")
    if weight_fanout < 1:
        raise ValueError(f"weight fanout must be >= 1, got {weight_fanout}")
    if weight_chunk_bytes < 1:
        raise ValueError(
            f"weight chunk bytes must be >= 1, got {weight_chunk_bytes}")
    return {
        "KUBEDL_RL_ROLE": role,
        "KUBEDL_RL_ACTORS": str(n_actors),
        "KUBEDL_RL_ACTOR_INDEX": str(index if role == "actor" else -1),
        "KUBEDL_RL_LEARNER_ADDR": learner_addr if role == "actor" else "",
        "KUBEDL_RL_ACTOR_ADDRS": actor_addrs if role == "learner" else "",
        "KUBEDL_WEIGHTS_FANOUT": str(weight_fanout),
        "KUBEDL_WEIGHTS_CHUNK_BYTES": str(weight_chunk_bytes),
    }


@dataclass
class SliceInfo:
    """One physical slice in the pool."""

    name: str
    type: SliceType
    reserved_by: Optional[str] = None  # gang key holding the whole slice

    @property
    def num_hosts(self) -> int:
        return self.type.num_hosts
