"""Local pod executor — the framework's kubelet.

The reference delegates pod execution to Kubernetes kubelets; this framework
is standalone, so the executor watches Pod objects and runs their containers
as real host processes: Pending -> Running (Ready condition stamped for
launch-delay metrics, ref pkg/metrics/job_metrics.go:139-194) ->
Succeeded/Failed with per-container exit codes, honoring pod-level restart
policies (Always/OnFailure restart in place with restart_count accrual, the
behavior pastBackoffLimit sums over — ref job.go:282-319).

Container images are not pulled: `command`+`args` run directly on the host,
which is exactly what CI needs (SURVEY.md §4: distribution is simulated
process-level). emptyDir volumes map to per-pod temp dirs.
"""
from __future__ import annotations

import logging
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.meta import now
from kubedl_tpu.api.pod import (
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
    PodCondition,
    PodPhase,
    PodRestartPolicy,
)
from kubedl_tpu.core.store import ADDED, DELETED, Conflict, NotFound, ObjectStore, write_status
from kubedl_tpu.analysis.witness import new_lock

log = logging.getLogger("kubedl_tpu.executor")


@dataclass
class _RunningPod:
    pod: Pod
    procs: Dict[str, subprocess.Popen] = field(default_factory=dict)
    restart_counts: Dict[str, int] = field(default_factory=dict)
    workdir: str = ""
    stop: bool = False
    thread: Optional[threading.Thread] = None


class LocalPodExecutor:
    """Runs pods as host processes, reflecting status back into the store."""

    def __init__(
        self,
        store: ObjectStore,
        scheduler=None,
        restart_backoff: float = 0.05,
        launch_hook=None,
        log_dir: Optional[str] = None,
        trace_root: Optional[str] = None,
    ) -> None:
        self.store = store
        # Optional TPU-slice scheduler (gang admission): pod stays Pending
        # until scheduler.assign(pod) returns a placement.
        self.scheduler = scheduler
        self.restart_backoff = restart_backoff
        self.launch_hook = launch_hook  # test seam: fn(pod) -> env overrides
        # container stdout/stderr land here (kubectl-logs equivalent),
        # appended across in-place restarts, removed when the pod is deleted
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="kubedl-logs-")
        # flight recorder (obs/): per-JOB trace dirs under this root,
        # injected as KUBEDL_TRACE_DIR/_ID the same way KUBEDL_CONTROL_DIR
        # travels. Job-scoped, NOT removed with the pod — the recorder's
        # whole point is that the timeline survives the pods (the operator
        # exports its control-plane spans into the same dirs).
        self.trace_root = trace_root or tempfile.mkdtemp(prefix="kubedl-trace-")
        # per-pod control channel (the local analog of a sidecar/ConfigMap
        # watch): the scheduler posts JSON messages (live-reshard RESIZE,
        # sched/capacity.py) into the pod's dir, injected as
        # KUBEDL_CONTROL_DIR; the workload replies next to the message.
        # Survives in-place restarts, removed with the pod.
        self.control_root = tempfile.mkdtemp(prefix="kubedl-ctl-")
        self._control_seq = 0
        # transport plane selection + auth (docs/transport.md), injected
        # the same way KUBEDL_CONTROL_DIR travels: the local executor
        # defaults to the dir transport (shared filesystem IS the local
        # analog of DCN); kube manifests pin KUBEDL_TRANSPORT=socket.
        # The auth token is per JOB — every pod of a gang shares it, two
        # jobs never do — minted lazily on first launch.
        self.transport = os.environ.get("KUBEDL_TRANSPORT", "dir")
        self._job_tokens: Dict[str, str] = {}
        self._running: Dict[str, _RunningPod] = {}
        self._lock = new_lock("executor.local.LocalPodExecutor._lock")
        self._stop = threading.Event()
        self._watch = None
        self._thread: Optional[threading.Thread] = None

    # -- logs ------------------------------------------------------------

    def _pod_log_dir(self, namespace: str, name: str) -> str:
        return os.path.join(self.log_dir, f"{namespace}_{name}")

    def read_logs(
        self, namespace: str, name: str, container: Optional[str] = None,
        tail: Optional[int] = None,
    ) -> str:
        """Concatenated logs of one pod (optionally one container)."""
        d = self._pod_log_dir(namespace, name)
        try:
            files = sorted(os.listdir(d))
        except OSError:
            return ""
        if container is not None:
            files = [f for f in files if f == f"{container}.log"]
        chunks = []
        for f in files:
            try:
                with open(os.path.join(d, f), "r", errors="replace") as fh:
                    chunks.append(fh.read())
            except OSError:
                continue
        text = "".join(chunks)
        if tail is not None:
            # tail=0 means "no lines" (kubectl semantics); [-0:] would be all
            text = "\n".join(text.splitlines()[-tail:]) if tail > 0 else ""
        return text

    # -- control channel -------------------------------------------------

    def control_dir(self, namespace: str, name: str) -> str:
        d = os.path.join(self.control_root, f"{namespace}_{name}")
        os.makedirs(d, exist_ok=True)
        return d

    def post_control(self, namespace: str, name: str, message: Dict) -> Optional[str]:
        """Post a control message to a RUNNING pod; returns the absolute
        reply path the workload will write (reshard_runtime.ReshardControl
        conventions), or None when the pod is not running here. Atomic
        tmp+rename so the poller never parses a half-written message."""
        with self._lock:
            if f"{namespace}/{name}" not in self._running:
                return None
            self._control_seq += 1
            seq = self._control_seq
        d = self.control_dir(namespace, name)
        msg = dict(message)
        msg.setdefault("reply", f"reply-{seq:06d}.json")
        tmp = os.path.join(d, f".msg-{seq:06d}.json.tmp")
        try:
            with open(tmp, "w") as f:
                import json

                json.dump(msg, f)
            os.replace(tmp, os.path.join(d, f"msg-{seq:06d}.json"))
        except OSError:
            return None
        return os.path.join(d, msg["reply"])

    def job_transport_token(self, namespace: str, job: str) -> str:
        """The job's shared transport auth token (KUBEDL_TRANSPORT_TOKEN)
        — one random secret per job, every pod of the gang gets the same
        one, so pods of DIFFERENT jobs cannot speak on each other's
        planes even on a shared host."""
        import secrets

        key = f"{namespace}/{job}"
        with self._lock:
            tok = self._job_tokens.get(key)
            if tok is None:
                tok = self._job_tokens[key] = secrets.token_hex(16)
            return tok

    def read_heartbeats(self) -> List[Dict]:
        """Latest step-telemetry heartbeat of every pod that wrote one
        (obs/steps.py StepStream writes ``heartbeat.json`` into the pod's
        control dir, atomic-replaced each step). Pull model: the operator's
        StepAggregator calls this on each metrics scrape."""
        import json

        out: List[Dict] = []
        try:
            entries = sorted(os.listdir(self.control_root))
        except OSError:
            return out
        for entry in entries:
            path = os.path.join(self.control_root, entry, "heartbeat.json")
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(rec, dict):
                continue
            namespace, _, pod = entry.partition("_")
            rec.setdefault("namespace", namespace)
            rec.setdefault("pod", pod)
            out.append(rec)
        return out

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._watch = self.store.watch(["Pod"])
        self._thread = threading.Thread(target=self._loop, name="executor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watch:
            self._watch.stop()
        with self._lock:
            entries = list(self._running.values())
        for entry in entries:
            self._kill(entry)
        if self._thread:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            ev = self._watch.next(timeout=0.1)
            if ev is None:
                continue
            key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
            if ev.type == ADDED:
                self._maybe_launch(key, ev.obj)
            elif ev.type == DELETED:
                with self._lock:
                    entry = self._running.pop(key, None)
                if entry:
                    self._kill(entry)
                if self.scheduler is not None:
                    self.scheduler.release(ev.obj)
                shutil.rmtree(
                    self._pod_log_dir(
                        ev.obj.metadata.namespace, ev.obj.metadata.name
                    ),
                    ignore_errors=True,
                )
                shutil.rmtree(
                    os.path.join(
                        self.control_root,
                        f"{ev.obj.metadata.namespace}_{ev.obj.metadata.name}",
                    ),
                    ignore_errors=True,
                )

    def _maybe_launch(self, key: str, pod: Pod) -> None:
        with self._lock:
            if key in self._running:
                return
            entry = _RunningPod(pod=pod)
            self._running[key] = entry
        entry.thread = threading.Thread(
            target=self._run_pod, args=(key, entry), name=f"pod-{key}", daemon=True
        )
        entry.thread.start()

    # -- pod run loop ----------------------------------------------------

    def _run_pod(self, key: str, entry: _RunningPod) -> None:
        pod = entry.pod
        try:
            # 1. schedule (TPU slice admission when configured)
            placement = None
            if self.scheduler is not None:
                while not self._stop.is_set() and not entry.stop:
                    placement = self.scheduler.assign(pod)
                    if placement is not None:
                        break
                    time.sleep(0.05)
                if placement is None:
                    return
            if entry.stop:
                return

            entry.workdir = tempfile.mkdtemp(prefix=f"kubedl-pod-{pod.metadata.name}-")
            volumes = self._prepare_volumes(pod, entry.workdir)

            # 2. init containers run sequentially to completion
            for c in pod.spec.init_containers:
                rc = self._run_container(entry, c, volumes, placement, wait=True)
                if rc is not None and rc < 0:
                    rc = 128 - rc  # signal death -> kubelet-style 128+signum
                if rc != 0:
                    self._set_status(
                        key, PodPhase.FAILED,
                        [ContainerStatus(name=c.name, terminated=ContainerStateTerminated(exit_code=rc, reason="InitError"))],
                        message=f"init container {c.name} failed with exit code {rc}",
                    )
                    return

            # 3. main containers; restart in place per pod restart policy
            while not entry.stop and not self._stop.is_set():
                started = now()
                for c in pod.spec.containers:
                    self._run_container(entry, c, volumes, placement, wait=False)
                self._set_status(
                    key, PodPhase.RUNNING,
                    [
                        ContainerStatus(name=c.name, ready=True,
                                        restart_count=entry.restart_counts.get(c.name, 0))
                        for c in pod.spec.containers
                    ],
                    ready=True, start_time=started, placement=placement,
                )
                exit_codes = {}
                for name, proc in list(entry.procs.items()):
                    rc = proc.wait()
                    # signal deaths surface as negative returncodes from
                    # Popen; kubelets report 128+signum (SIGTERM -> 143,
                    # which the ExitCode policy treats as retryable)
                    exit_codes[name] = 128 - rc if rc < 0 else rc
                if entry.stop or self._stop.is_set():
                    return
                failed = {n: rc for n, rc in exit_codes.items() if rc != 0}
                policy = pod.spec.restart_policy
                should_restart = policy == PodRestartPolicy.ALWAYS or (
                    policy == PodRestartPolicy.ON_FAILURE and failed
                )
                statuses = [
                    ContainerStatus(
                        name=n,
                        restart_count=entry.restart_counts.get(n, 0),
                        terminated=ContainerStateTerminated(
                            exit_code=rc, finished_at=now(),
                            reason="Error" if rc else "Completed",
                        ),
                    )
                    for n, rc in exit_codes.items()
                ]
                if should_restart:
                    for n in exit_codes:
                        entry.restart_counts[n] = entry.restart_counts.get(n, 0) + 1
                    # keep phase Running with accrued restart counts, like a
                    # kubelet in CrashLoopBackOff-free fast path
                    self._set_status(
                        key, PodPhase.RUNNING,
                        [
                            ContainerStatus(name=n, ready=False,
                                            restart_count=entry.restart_counts.get(n, 0),
                                            terminated=s.terminated)
                            for n, s in zip(exit_codes, statuses)
                        ],
                        placement=placement,
                    )
                    time.sleep(self.restart_backoff)
                    continue
                phase = PodPhase.FAILED if failed else PodPhase.SUCCEEDED
                self._set_status(key, phase, statuses, placement=placement)
                return
        except Exception:
            from kubedl_tpu.utils.joblog import pod_logger

            pod_logger(log, entry.pod).exception("executor failed running pod")
            self._set_status(
                key, PodPhase.FAILED,
                [ContainerStatus(name="executor", terminated=ContainerStateTerminated(exit_code=127, reason="ExecutorError"))],
            )
        finally:
            if self.scheduler is not None and entry.pod.spec.tpu_chips() > 0:
                self.scheduler.release(entry.pod)
            if entry.workdir:
                shutil.rmtree(entry.workdir, ignore_errors=True)
            with self._lock:
                self._running.pop(key, None)

    def _prepare_volumes(self, pod: Pod, workdir: str) -> Dict[str, str]:
        paths = {}
        for vol in pod.spec.volumes:
            if vol.kind == "hostPath":
                paths[vol.name] = vol.host_path
            else:
                p = os.path.join(workdir, "vol", vol.name)
                os.makedirs(p, exist_ok=True)
                paths[vol.name] = p
        return paths

    def _localize_service_dns(self, env: Dict[str, str]) -> None:
        """The local-executor equivalent of cluster DNS: every pod runs on
        this host, so a simple `host` / `host:port` env value whose host is
        a headless-service DNS name (`name.ns.svc[...]`, ref
        tensorflow.go:122-136) — e.g. torch's MASTER_ADDR — rewrites to
        127.0.0.1. Consumers like torch c10d cannot resolve the cluster
        name themselves (the JAX coordinator does its own fallback,
        train/coordinator.py). JSON blobs (TF_CONFIG) are left alone."""
        import re

        services = {s.metadata.name for s in self.store.list("Service")}

        def local(host: str) -> str:
            # only a BARE hostname is eligible — host lists, URLs, or
            # suffixed addresses ("a.svc,b.svc", "zk.svc:2181/chroot")
            # pass through untouched rather than collapsing to an IP
            if not re.fullmatch(r"[A-Za-z0-9.-]+", host):
                return host
            first, _, rest = host.partition(".")
            if first in services and ".svc" in rest:
                return "127.0.0.1"
            return host

        for key, val in list(env.items()):
            if not isinstance(val, str) or "." not in val:
                continue
            host, sep, port = val.partition(":")
            if sep and port.isdigit():
                env[key] = f"{local(host)}{sep}{port}"
            else:
                env[key] = local(val)

    def _run_container(self, entry: _RunningPod, container, volumes, placement, wait: bool):
        pod = entry.pod
        env = dict(os.environ)
        env.update(container.env)
        env["POD_NAME"] = pod.metadata.name
        env["POD_NAMESPACE"] = pod.metadata.namespace
        env["KUBEDL_CONTROL_DIR"] = self.control_dir(
            pod.metadata.namespace, pod.metadata.name)
        # flight-recorder correlation (obs/trace.py): one gang-level trace
        # id + a shared per-job trace dir for every pod of the job, so the
        # control-plane and compute-plane spans merge into one timeline.
        # setdefault: a manifest that pins its own KUBEDL_TRACE_* wins.
        from kubedl_tpu.obs.trace import job_trace_dir, trace_id_for

        job_name = pod.metadata.labels.get("job-name") or pod.metadata.name
        # transport selection + per-job auth token (docs/transport.md);
        # setdefault — a manifest that pins its own transport env wins
        env.setdefault("KUBEDL_TRANSPORT", self.transport)
        env.setdefault("KUBEDL_TRANSPORT_TOKEN", self.job_transport_token(
            pod.metadata.namespace, job_name))
        trace_dir = job_trace_dir(
            self.trace_root, pod.metadata.namespace, job_name)
        try:
            os.makedirs(trace_dir, exist_ok=True)
            env.setdefault("KUBEDL_TRACE_DIR", trace_dir)
            env.setdefault(
                "KUBEDL_TRACE_ID",
                trace_id_for(pod.metadata.namespace, job_name))
        except OSError:
            pass  # recorder unavailable; the pod still runs
        for k, v in pod.metadata.labels.items():
            env[f"KUBEDL_LABEL_{k.upper().replace('-', '_')}"] = v
        if placement is not None:
            env.update(placement.env())
        if self.launch_hook is not None:
            env.update(self.launch_hook(pod) or {})
        # volume mounts exported as env so host processes can find them
        for vm in container.volume_mounts:
            if vm.name in volumes:
                env[f"KUBEDL_VOLUME_{vm.name.upper().replace('-', '_')}"] = volumes[vm.name]
        self._localize_service_dns(env)
        # Local mode has no container images: make the framework's own
        # runtime modules (kubedl_tpu.train.*) importable from any cwd,
        # merging with (not clobbering) any user-set PYTHONPATH.
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH", "")
        if pkg_parent not in existing.split(os.pathsep):
            env["PYTHONPATH"] = f"{pkg_parent}{os.pathsep}{existing}" if existing else pkg_parent
        argv = list(container.command) + list(container.args)
        if not argv:
            if "GIT_SYNC_REPO" in container.env:
                # an injected git-sync init container relies on its image
                # entrypoint on a cluster; locally there is no image, so run
                # the native sync runner (codesync/git_sync.py) instead
                argv = [sys.executable, "-m", "kubedl_tpu.codesync.git_sync"]
            else:
                argv = ["true"]
        cwd = container.working_dir or entry.workdir
        log_dir = self._pod_log_dir(pod.metadata.namespace, pod.metadata.name)
        os.makedirs(log_dir, exist_ok=True)
        log_fh = open(os.path.join(log_dir, f"{container.name}.log"), "ab")
        try:
            proc = subprocess.Popen(
                argv, env=env, cwd=cwd,
                stdout=log_fh, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            log_fh.close()  # child holds its own fd
        if wait:
            return proc.wait()
        entry.procs[container.name] = proc
        return None

    def _kill(self, entry: _RunningPod) -> None:
        entry.stop = True
        for proc in entry.procs.values():
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + 2.0
        for proc in entry.procs.values():
            remaining = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(remaining, 0.1))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    # -- status write ----------------------------------------------------

    def _set_status(
        self, key: str, phase: PodPhase, container_statuses: List[ContainerStatus],
        ready: bool = False, start_time: Optional[float] = None,
        placement=None, message: str = "",
    ) -> None:
        namespace, name = key.split("/", 1)
        for _ in range(5):
            try:
                pod = self.store.get("Pod", namespace, name)
            except NotFound:
                return
            pod.status.phase = phase
            pod.status.container_statuses = container_statuses
            pod.status.message = message
            if start_time is not None and pod.status.start_time is None:
                pod.status.start_time = start_time
            if ready and pod.status.ready_time() is None:
                pod.status.conditions = [
                    c for c in pod.status.conditions if c.type != "Ready"
                ] + [PodCondition(type="Ready", status="True", last_transition_time=now())]
            if placement is not None:
                pod.status.node_name = placement.node_name
                pod.status.tpu_slice = placement.slice_name
                pod.status.tpu_worker_id = placement.worker_id
            try:
                write_status(self.store, pod)
                return
            except Conflict:
                continue
        log.warning("status update for pod %s kept conflicting", key)
