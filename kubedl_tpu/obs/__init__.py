"""Flight recorder (docs/observability.md): cross-plane trace spans
(obs/trace.py), per-step telemetry + straggler detection (obs/steps.py),
and goodput accounting over the span timeline (obs/goodput.py)."""
from kubedl_tpu.obs.goodput import GoodputReporter, classify, goodput
from kubedl_tpu.obs.steps import StepAggregator, StepStream, load_step_records
from kubedl_tpu.obs.trace import (
    ENV_TRACE_DIR,
    ENV_TRACE_ID,
    Tracer,
    chrome_trace,
    job_trace_dir,
    load_spans,
    trace_id_for,
    tracer_from_env,
)

__all__ = [
    "ENV_TRACE_DIR",
    "ENV_TRACE_ID",
    "GoodputReporter",
    "StepAggregator",
    "StepStream",
    "Tracer",
    "chrome_trace",
    "classify",
    "goodput",
    "job_trace_dir",
    "load_spans",
    "load_step_records",
    "trace_id_for",
    "tracer_from_env",
]
