"""Flight-recorder trace spans — the cross-plane timeline primitive.

An OTel-shaped but dependency-free span API: every interesting interval
(gang queue wait, reconcile, checkpoint save, a train step, a reshard
ladder rung) becomes one JSON record

    {"name", "trace_id", "span_id", "parent_id", "service",
     "ts" (epoch seconds), "dur" (seconds), "attrs": {...}}

kept in a bounded in-process ring buffer and appended to a JSONL file.
Durations come from the monotonic clock (``perf_counter``); ``ts`` is the
wall clock, which is the shared axis that lets the operator process and
its workload pods — separate OS processes on the local executor — merge
into one timeline.

Correlation works the way ``KUBEDL_CONTROL_DIR`` already travels: the
executor derives a deterministic gang-level trace id from the job key and
injects ``KUBEDL_TRACE_ID`` + a per-job ``KUBEDL_TRACE_DIR`` into every
container, while the operator's tracer routes its own spans into the same
per-job directory (``operator.jsonl``). `kubedl-tpu trace <job>` and the
goodput accountant (obs/goodput.py) read the merged directory back.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

ENV_TRACE_DIR = "KUBEDL_TRACE_DIR"
ENV_TRACE_ID = "KUBEDL_TRACE_ID"

# step-record streams (obs/steps.py) share the trace dir but are NOT
# spans; load_spans must skip them
STEP_SUFFIX = ".steps.jsonl"


def trace_id_for(namespace: str, name: str) -> str:
    """Deterministic gang-level trace id: stable across pod restarts and
    preemption re-admissions, so one job's whole life — including the
    downtime — is ONE timeline."""
    return hashlib.sha1(f"{namespace}/{name}".encode()).hexdigest()[:32]


def job_trace_dir(root: str, namespace: str, name: str) -> str:
    """The per-job trace directory both planes agree on (the executor
    injects it as KUBEDL_TRACE_DIR; the operator exports into it)."""
    return os.path.join(root, f"{namespace}_{name}")


class Span:
    """One open span; finishes on end() or context-manager exit (an
    exception stamps an ``error`` attribute before closing)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "service",
                 "ts", "attrs", "_tracer", "_t0", "_done")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str, attrs: Dict) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = tracer._next_id()
        self.parent_id = parent_id
        self.service = tracer.service
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self.attrs = dict(attrs)
        self._done = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self) -> Dict:
        if self._done:
            return {}
        self._done = True
        dur = time.perf_counter() - self._t0
        return self._tracer._finish(self, dur)

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._tracer._pop(self)
        if exc is not None:
            self.attrs.setdefault("error", f"{type(exc).__name__}: {exc}"[:200])
        self.end()


class Tracer:
    """Bounded flight recorder: in-process ring + optional JSONL export.

    Export modes (at most one):
      * ``export_path`` — every span appends to ONE file (workload pods:
        ``<KUBEDL_TRACE_DIR>/<pod>.jsonl``);
      * ``export_root`` — spans route per job into
        ``<root>/<ns>_<job>/<service>.jsonl`` using their ``namespace``/
        ``job`` attrs (the operator's control-plane tracer); spans with
        no job attr stay ring-only.

    ``max_export_spans`` bounds the file footprint PER FILE: past it,
    spans keep landing in the ring but stop being written to that file
    (``dropped`` counts them) — the recorder degrades to a ring, it
    never grows without bound. The budget is per file, not fleet-wide:
    a long-lived operator's reconcile churn on old jobs must never
    silence the queue-wait evidence of a NEW job's timeline.
    """

    def __init__(
        self,
        service: str = "",
        trace_id: str = "",
        export_path: Optional[str] = None,
        export_root: Optional[str] = None,
        ring_size: int = 2048,
        max_export_spans: int = 20000,
    ) -> None:
        self.service = service
        self.trace_id = trace_id
        self.export_path = export_path
        self.export_root = export_root
        self.max_export_spans = max_export_spans
        self._ring: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._files: Dict[str, object] = {}
        self._exported: Dict[str, int] = {}  # per export file
        self.dropped = 0
        self._ids = itertools.count(1)
        self._local = threading.local()

    @property
    def exporting(self) -> bool:
        return bool(self.export_path or self.export_root)

    # -- span lifecycle --------------------------------------------------

    def _next_id(self) -> str:
        return f"{os.getpid():x}-{next(self._ids):x}"

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def span(self, name: str, trace_id: Optional[str] = None, **attrs) -> Span:
        """Open a span (use as a context manager for nesting: the parent
        is whatever span the calling thread currently has open). Children
        inherit the parent's trace id and job/namespace routing attrs, so
        a nested span lands in the same per-job file."""
        parent = self.current()
        if parent is not None:
            for key in ("job", "namespace"):
                if key in parent.attrs and key not in attrs:
                    attrs[key] = parent.attrs[key]
        return Span(
            self, name,
            trace_id=trace_id or (parent.trace_id if parent else "") or self.trace_id,
            parent_id=parent.span_id if parent else "",
            attrs=attrs,
        )

    def record(
        self,
        name: str,
        duration_s: float = 0.0,
        end_ts: Optional[float] = None,
        trace_id: Optional[str] = None,
        **attrs,
    ) -> Dict:
        """Retroactively record a finished interval (e.g. a queue wait
        measured from monotonic timestamps): ``ts`` is back-dated so the
        span COVERS the interval that just ended."""
        end_ts = time.time() if end_ts is None else end_ts
        rec = {
            "name": name,
            "trace_id": trace_id if trace_id is not None else self.trace_id,
            "span_id": self._next_id(),
            "parent_id": "",
            "service": self.service,
            "ts": end_ts - max(duration_s, 0.0),
            "dur": max(duration_s, 0.0),
            "attrs": dict(attrs),
        }
        self._commit(rec)
        return rec

    def _finish(self, span: Span, dur: float) -> Dict:
        rec = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "service": span.service,
            "ts": span.ts,
            "dur": dur,
            "attrs": span.attrs,
        }
        self._commit(rec)
        return rec

    # -- sinks -----------------------------------------------------------

    def _commit(self, rec: Dict) -> None:
        with self._lock:
            self._ring.append(rec)
            path = self._path_for(rec)
            if path is None:
                return
            if self._exported.get(path, 0) >= self.max_export_spans:
                self.dropped += 1
                return
            try:
                fh = self._files.get(path)
                if fh is None:
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    fh = self._files[path] = open(path, "a")
                fh.write(json.dumps(rec, default=str) + "\n")
                fh.flush()
                self._exported[path] = self._exported.get(path, 0) + 1
            except OSError:
                self.dropped += 1

    def _path_for(self, rec: Dict) -> Optional[str]:
        if self.export_path:
            return self.export_path
        if self.export_root:
            job = rec["attrs"].get("job")
            if not job:
                return None
            namespace = rec["attrs"].get("namespace", "default")
            return os.path.join(
                job_trace_dir(self.export_root, namespace, job),
                f"{self.service or 'operator'}.jsonl",
            )
        return None

    def spans(self) -> List[Dict]:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            for fh in self._files.values():
                try:
                    fh.close()
                except OSError:
                    pass
            self._files.clear()


def tracer_from_env(service: str = "") -> Tracer:
    """Workload-side tracer from the injected env: exports to
    ``<KUBEDL_TRACE_DIR>/<service>.jsonl`` with the gang trace id from
    ``KUBEDL_TRACE_ID``. Without the env the tracer stays ring-only
    (``exporting`` False), so uninstrumented runs pay nothing."""
    service = service or os.environ.get("POD_NAME", "") or f"pid-{os.getpid()}"
    d = os.environ.get(ENV_TRACE_DIR, "")
    path = None
    if d:
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"{service}.jsonl")
        except OSError:
            path = None
    return Tracer(
        service=service,
        trace_id=os.environ.get(ENV_TRACE_ID, ""),
        export_path=path,
    )


def load_spans(trace_dir: str) -> List[Dict]:
    """Merge every span JSONL in a job's trace dir, sorted by start time.
    Step-record streams (``*.steps.jsonl``) and unparseable lines are
    skipped — a half-written tail line must not sink the whole timeline."""
    spans: List[Dict] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return spans
    for fname in names:
        if not fname.endswith(".jsonl") or fname.endswith(STEP_SUFFIX):
            continue
        try:
            with open(os.path.join(trace_dir, fname)) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "name" in rec and "ts" in rec:
                        rec.setdefault("dur", 0.0)
                        rec.setdefault("attrs", {})
                        spans.append(rec)
        except OSError:
            continue
    spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("name", "")))
    return spans


def chrome_trace(spans: List[Dict]) -> Dict:
    """Chrome trace-event JSON (load in Perfetto / chrome://tracing):
    one complete ("X") event per span, microsecond timestamps, plus "M"
    metadata naming the pid (trace id / job) and tid (service) rows."""
    events: List[Dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    for s in spans:
        pkey = s["attrs"].get("job") or s.get("trace_id") or "trace"
        pid = pids.get(pkey)
        if pid is None:
            pid = pids[pkey] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": str(pkey)}})
        tkey = (pid, s.get("service", ""))
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": s.get("service", "") or "?"}})
        events.append({
            "name": s.get("name", ""),
            "cat": s.get("service", "") or "span",
            "ph": "X",
            "ts": float(s.get("ts", 0.0)) * 1e6,
            "dur": max(float(s.get("dur", 0.0)), 0.0) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": {k: v for k, v in s.get("attrs", {}).items()},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
