"""Goodput accounting — fold a job's span timeline into "where did the
time go" buckets.

Wall time is the window from the first span's start to the last span's
end. Every instant inside the window is attributed to exactly ONE bucket
(overlaps resolve by precedence — e.g. an async checkpoint save that
overlaps a train step counts as checkpoint, not double-counted), so the
breakdown sums to wall time exactly:

  queue_wait    first gang admission wait (gang.queue_wait, cause=initial)
  eviction      preemption downtime: requeue waits + drain after eviction
  reshard       RESIZE ladder rungs (live / staged / fallback), both planes
  checkpoint    Orbax save/restore stalls
  init_compile  process bootstrap + first-step XLA compile
  actor_starved   RL fleet: learner waiting on an empty trajectory queue
  learner_starved RL fleet: actors parked waiting for a weight broadcast
  weight_sync   RL fleet: weight broadcast publish/adopt time
  rollout       RL fleet: actor generation time (rl.rollout spans)
  steps         productive train-step time — the goodput numerator
                (rl.learn, the learner's update, lands here)
  other         window time no span covers (process spawn, scheduler gaps)

``kubedl_goodput_ratio{job}`` = steps / wall.

The RL starvation buckets sit ABOVE rollout/steps in precedence on
purpose: an actor/learner fleet runs two concurrent planes on one
timeline, and the coupling evidence is exactly the instant where one
side waits — a learner starving WHILE actors are mid-rollout is
actor-starved time (the actors can't keep up), an actor parked for
weights while the learner is mid-step is learner-starved time. A fleet
whose wall time pools in actor_starved needs more/faster actors; one
pooling in learner_starved needs a faster learner — distinguishable
buckets, which is the ROADMAP item's acceptance evidence.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from kubedl_tpu.obs.trace import job_trace_dir, load_spans

# attribution precedence, highest first: an instant covered by several
# categories lands in the earliest listed one (see module docstring for
# why the RL starvation buckets outrank rollout/steps)
BUCKETS = ("queue_wait", "eviction", "reshard", "checkpoint",
           "init_compile", "actor_starved", "learner_starved",
           "weight_sync", "rollout", "steps")
OTHER = "other"


def classify(span: Dict) -> Optional[str]:
    """Map one span to its goodput bucket (None = uncategorized)."""
    name = span.get("name", "")
    attrs = span.get("attrs", {}) or {}
    if name == "gang.queue_wait":
        return "eviction" if attrs.get("cause") == "requeue" else "queue_wait"
    if name.startswith("reshard.") or name == "sched.reshard":
        return "reshard"
    if name in ("ckpt.save", "ckpt.restore"):
        return "checkpoint"
    if name in ("trainer.init", "train.compile"):
        return "init_compile"
    if name == "rl.idle":
        cause = attrs.get("cause", "")
        if cause in ("actor_starved", "learner_starved"):
            return cause
        return None
    if name == "rl.weight_sync":
        return "weight_sync"
    if name == "rl.rollout":
        return "rollout"
    if name in ("train.step", "pipeline.step", "rl.learn"):
        return "steps"
    return None


def goodput(spans: List[Dict]) -> Dict:
    """Sweep-line attribution over categorized span intervals.

    Returns ``{"wall_s", "ratio", "buckets": {...bucket: seconds...,
    "other": seconds}, "trace_ids", "t0", "t1", "spans"}``; the bucket
    values partition ``wall_s`` exactly.
    """
    empty = {
        "wall_s": 0.0, "ratio": 0.0,
        "buckets": {b: 0.0 for b in (*BUCKETS, OTHER)},
        "trace_ids": [], "t0": 0.0, "t1": 0.0, "spans": 0,
    }
    if not spans:
        return empty
    # The wall window spans the CATEGORIZED timeline (queue wait through
    # the last step/checkpoint/reshard), falling back to all spans only
    # when nothing classifies. Uncategorized spans must not stretch it:
    # the operator keeps appending reconcile spans to a Succeeded job's
    # dir until its TTL, and a window that grew with them would make a
    # finished job's goodput ratio decay depending on WHEN you scrape.
    windowed = [s for s in spans if classify(s) is not None] or spans
    t0 = min(float(s.get("ts", 0.0)) for s in windowed)
    t1 = max(float(s.get("ts", 0.0)) + max(float(s.get("dur", 0.0)), 0.0)
             for s in windowed)
    wall = max(t1 - t0, 0.0)
    if wall <= 0.0:
        out = dict(empty)
        out.update({"t0": t0, "t1": t1, "spans": len(spans),
                    "trace_ids": sorted({s.get("trace_id", "")
                                         for s in spans} - {""})})
        return out
    # boundary events: (time, +1/-1, bucket index)
    events: List[tuple] = []
    for s in spans:
        bucket = classify(s)
        dur = max(float(s.get("dur", 0.0)), 0.0)
        if bucket is None or dur <= 0.0:
            continue
        start = max(float(s.get("ts", 0.0)), t0)
        end = min(start + dur, t1)
        if end <= start:
            continue
        idx = BUCKETS.index(bucket)
        events.append((start, 1, idx))
        events.append((end, -1, idx))
    buckets = {b: 0.0 for b in BUCKETS}
    buckets[OTHER] = 0.0
    active = [0] * len(BUCKETS)
    covered = 0.0
    events.sort(key=lambda e: e[0])
    prev = t0
    i = 0
    while i < len(events):
        t = events[i][0]
        if t > prev:
            # attribute [prev, t) to the highest-precedence active bucket
            for idx, n in enumerate(active):
                if n > 0:
                    buckets[BUCKETS[idx]] += t - prev
                    covered += t - prev
                    break
            prev = t
        while i < len(events) and events[i][0] == t:
            _, delta, idx = events[i]
            active[idx] += delta
            i += 1
    # tail after the last event (only when uncategorized spans extend t1)
    if t1 > prev:
        for idx, n in enumerate(active):
            if n > 0:
                buckets[BUCKETS[idx]] += t1 - prev
                covered += t1 - prev
                break
        prev = t1
    buckets[OTHER] = max(wall - covered, 0.0)
    return {
        "wall_s": wall,
        "ratio": buckets["steps"] / wall,
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "trace_ids": sorted({s.get("trace_id", "") for s in spans} - {""}),
        "t0": t0,
        "t1": t1,
        "spans": len(spans),
    }


class GoodputReporter:
    """Per-job goodput over a flight-recorder root, for the metrics
    scrape (RuntimeMetrics.register_goodput) and ``/debug/vars``.

    Each job dir is recomputed only when its span files changed (size
    fingerprint) — a scrape over a quiet recorder costs a few stats.
    ``snapshot()`` covers at most ``max_jobs`` dirs (most recently
    modified first), so series cardinality and scrape cost stay bounded
    on an operator that has run thousands of jobs; ``job()`` still reads
    any dir directly (the /trace endpoint has no such cap)."""

    def __init__(self, root: str, max_jobs: int = 200) -> None:
        self.root = root
        self.max_jobs = int(max_jobs)
        self._lock = threading.Lock()
        self._cache: Dict[str, tuple] = {}  # dir -> (fingerprint, result)

    def _fingerprint(self, d: str) -> tuple:
        total = 0
        n = 0
        try:
            for entry in os.scandir(d):
                if entry.name.endswith(".jsonl"):
                    try:
                        total += entry.stat().st_size
                        n += 1
                    except OSError:
                        continue
        except OSError:
            return (0, 0)
        return (n, total)

    def job(self, namespace: str, name: str) -> Dict:
        return self._for_dir(job_trace_dir(self.root, namespace, name))

    def _for_dir(self, d: str) -> Dict:
        fp = self._fingerprint(d)
        with self._lock:
            cached = self._cache.get(d)
            if cached is not None and cached[0] == fp:
                return cached[1]
        result = goodput(load_spans(d))
        with self._lock:
            self._cache[d] = (fp, result)
        return result

    def snapshot(self) -> Dict:
        """{"jobs": {"ns/name": goodput dict}} over the most recently
        active ``max_jobs`` recorded jobs."""
        out: Dict = {"jobs": {}}
        try:
            entries = [e for e in os.scandir(self.root) if e.is_dir()]
        except OSError:
            return out

        def mtime(e):
            try:
                return e.stat().st_mtime
            except OSError:
                return 0.0

        entries.sort(key=mtime, reverse=True)
        stale = entries[self.max_jobs:]
        entries = entries[:self.max_jobs]
        if stale:
            with self._lock:
                for e in stale:  # keep the cache bounded too
                    self._cache.pop(os.path.join(self.root, e.name), None)
        for entry in sorted(entries, key=lambda e: e.name):
            namespace, _, job = entry.name.partition("_")
            if not job:
                continue
            gp = self._for_dir(os.path.join(self.root, entry.name))
            if gp["spans"]:
                out["jobs"][f"{namespace}/{job}"] = gp
        return out
