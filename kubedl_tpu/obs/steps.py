"""Per-step telemetry stream + cross-pod straggler aggregation.

Compute-plane side (StepStream): the trainer emits one bounded record per
train step — step time, data wait, compile events, checkpoint stall, loss
— to its pod's ``<KUBEDL_TRACE_DIR>/<pod>.steps.jsonl`` AND, as a
latest-value heartbeat, to ``<KUBEDL_CONTROL_DIR>/heartbeat.json``
(atomic tmp+rename, the reshard control channel's write discipline).

Control-plane side (StepAggregator): the operator scans the executor's
control dirs for heartbeats on each metrics scrape (pull model — no extra
loop to race) and folds them into per-job step-time series and straggler
detection: a pod whose last step time exceeds ``k``x the job median is
flagged. Rendered as ``kubedl_step_time_seconds`` /
``kubedl_straggler_pods`` / ``kubedl_compile_events_total``
(metrics/runtime_metrics.py) and under ``steps`` in ``/debug/vars``.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Callable, Dict, List, Optional

from kubedl_tpu.obs.trace import ENV_TRACE_DIR, STEP_SUFFIX
from kubedl_tpu.analysis.witness import new_lock

HEARTBEAT_FILE = "heartbeat.json"


class StepStream:
    """Bounded per-pod step-record stream + heartbeat writer."""

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        heartbeat_path: Optional[str] = None,
        job: str = "",
        namespace: str = "",
        pod: str = "",
        max_records: int = 100_000,
    ) -> None:
        self.jsonl_path = jsonl_path
        self.heartbeat_path = heartbeat_path
        self.job = job
        self.namespace = namespace
        self.pod = pod
        self.max_records = max_records
        self.written = 0
        self.dropped = 0
        self.compiles = 0  # cumulative compile events this incarnation
        self._fh = None

    @classmethod
    def from_env(cls, pod: str = "") -> Optional["StepStream"]:
        """Build from the operator-injected env; None when neither a
        trace dir nor a control dir was injected (nothing to write to)."""
        trace_dir = os.environ.get(ENV_TRACE_DIR, "")
        control_dir = os.environ.get("KUBEDL_CONTROL_DIR", "")
        if not trace_dir and not control_dir:
            return None
        pod = pod or os.environ.get("POD_NAME", "") or f"pid-{os.getpid()}"
        jsonl = None
        if trace_dir:
            try:
                os.makedirs(trace_dir, exist_ok=True)
                jsonl = os.path.join(trace_dir, f"{pod}{STEP_SUFFIX}")
            except OSError:
                jsonl = None
        heartbeat = (os.path.join(control_dir, HEARTBEAT_FILE)
                     if control_dir else None)
        return cls(
            jsonl_path=jsonl,
            heartbeat_path=heartbeat,
            job=os.environ.get("KUBEDL_LABEL_JOB_NAME", ""),
            namespace=os.environ.get("POD_NAMESPACE", ""),
            pod=pod,
        )

    def record(
        self,
        step: int,
        step_s: float,
        data_s: float = 0.0,
        loss: Optional[float] = None,
        compile: bool = False,
        ckpt_s: float = 0.0,
    ) -> Dict:
        if compile:
            self.compiles += 1
        rec = {
            "job": self.job,
            "namespace": self.namespace,
            "pod": self.pod,
            "step": int(step),
            "step_s": round(float(step_s), 6),
            "data_s": round(float(data_s), 6),
            "ckpt_s": round(float(ckpt_s), 6),
            "compile": bool(compile),
            "compiles": self.compiles,
            "t": time.time(),
        }
        if loss is not None:
            rec["loss"] = float(loss)
        line = json.dumps(rec)
        if self.jsonl_path:
            if self.written >= self.max_records:
                self.dropped += 1
            else:
                try:
                    if self._fh is None:
                        self._fh = open(self.jsonl_path, "a")
                    self._fh.write(line + "\n")
                    self._fh.flush()
                    self.written += 1
                except OSError:
                    self.dropped += 1
        if self.heartbeat_path:
            # latest-value heartbeat: atomic replace so the operator's
            # scan never parses a half-written record
            tmp = self.heartbeat_path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    f.write(line)
                os.replace(tmp, self.heartbeat_path)
            except OSError:
                pass
        return rec

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def load_step_records(jsonl_path: str) -> List[Dict]:
    """Read one pod's step stream back (unparseable tail lines skipped)."""
    out: List[Dict] = []
    try:
        with open(jsonl_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "step" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


class StepAggregator:
    """Cross-pod step aggregation + straggler detection.

    ``scan_fn`` (e.g. LocalPodExecutor.read_heartbeats) supplies the live
    heartbeat records on each snapshot; ``observe`` feeds records
    directly (tests, in-process lanes). A pod is a straggler when its
    last step time exceeds ``k`` x the median of its PEERS' step times
    (leave-one-out: including the candidate in the median would make a
    2-pod gang's straggler mathematically undetectable for k >= 2), with
    at least ``min_pods`` reporting pods (a lone pod has no peer
    baseline to straggle against).
    """

    def __init__(
        self,
        scan_fn: Optional[Callable[[], List[Dict]]] = None,
        k: float = 2.0,
        min_pods: int = 2,
        max_age_s: float = 3600.0,
    ) -> None:
        self.scan_fn = scan_fn
        self.k = float(k)
        self.min_pods = int(min_pods)
        # records older than this fall off the snapshot: deleted jobs'
        # heartbeats (their control dirs are rmtree'd with the pod) must
        # not export stale series forever. 0 disables pruning.
        self.max_age_s = float(max_age_s)
        self._lock = new_lock("obs.steps.StepAggregator._lock")
        # job key -> pod -> latest record
        self._jobs: Dict[str, Dict[str, Dict]] = {}

    @staticmethod
    def _job_key(rec: Dict) -> str:
        return f"{rec.get('namespace') or 'default'}/{rec.get('job') or '?'}"

    def observe(self, rec: Dict) -> None:
        if not isinstance(rec, dict) or "step_s" not in rec:
            return
        pod = str(rec.get("pod") or "?")
        with self._lock:
            pods = self._jobs.setdefault(self._job_key(rec), {})
            prev = pods.get(pod)
            # heartbeats are latest-value; never regress to an older one
            if prev is None or rec.get("t", 0.0) >= prev.get("t", 0.0):
                pods[pod] = dict(rec)

    def snapshot(self) -> Dict:
        if self.scan_fn is not None:
            try:
                for rec in self.scan_fn() or []:
                    self.observe(rec)
            except Exception:  # noqa: BLE001 — scan racing shutdown
                pass
        out: Dict = {"jobs": {}, "k": self.k, "min_pods": self.min_pods}
        with self._lock:
            if self.max_age_s > 0:
                # prune in place: the cardinality of /metrics and this
                # cache must not grow with every job ever run
                cutoff = time.time() - self.max_age_s
                for j in list(self._jobs):
                    pods = self._jobs[j]
                    for pod in [p for p, r in pods.items()
                                if r.get("t", 0.0) < cutoff]:
                        del pods[pod]
                    if not pods:
                        del self._jobs[j]
            jobs = {j: dict(pods) for j, pods in self._jobs.items()}
        for job, pods in sorted(jobs.items()):
            times = [p["step_s"] for p in pods.values()]
            median = statistics.median(times) if times else 0.0
            stragglers = []
            if len(pods) >= self.min_pods:
                for name, p in sorted(pods.items()):
                    peers = [t for n, t in
                             ((n2, p2["step_s"]) for n2, p2 in pods.items())
                             if n != name]
                    baseline = statistics.median(peers) if peers else 0.0
                    if baseline > 0 and p["step_s"] > self.k * baseline:
                        stragglers.append(name)
            out["jobs"][job] = {
                "pods": {
                    name: {
                        "step": p.get("step", 0),
                        "step_s": p.get("step_s", 0.0),
                        "data_s": p.get("data_s", 0.0),
                        "compiles": p.get("compiles", 0),
                        "loss": p.get("loss"),
                        "age_s": round(max(time.time() - p.get("t", 0.0), 0.0), 3),
                    }
                    for name, p in sorted(pods.items())
                },
                "median_step_s": median,
                "stragglers": stragglers,
                "compile_events": sum(p.get("compiles", 0) for p in pods.values()),
            }
        return out

    def forget(self, namespace: str, job: str) -> None:
        with self._lock:
            self._jobs.pop(f"{namespace}/{job}", None)

    def reset(self) -> None:
        with self._lock:
            self._jobs.clear()
