"""TPU-slice gang admission — all-or-nothing placement onto pod slices.

Replaces the reference's kube-batch PodGroup implementation
(ref pkg/gang_schedule/batch_scheduler/scheduler.go:59-99) with slice-atomic
admission: a gang reserves one whole TPU slice or nothing. Two reference
gaps are fixed deliberately:
  * SchedulingPolicy.MinAvailable is honored (the reference always used total
    replicas — scheduler.go:66-69);
  * admission is atomic at the slice, so the "expectations vs async gang"
    race (SURVEY.md §7 hard parts) collapses to: pods stay Pending until the
    reservation exists, then all start together.

The admitter implements both the GangScheduler plugin contract (used by the
reconciler engine) and the executor's scheduler protocol (assign/release).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import (
    LABEL_REPLICA_INDEX,
    LABEL_SLICE_ID,
    ReplicaSpec,
    slice_group,
)
from kubedl_tpu.api.meta import ObjectMeta
from kubedl_tpu.core.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    read_fresh,
    write_status,
)
from kubedl_tpu.executor.tpu_topology import (
    Placement,
    SliceInfo,
    host_coords,
    parse_slice_type,
    ring_order,
)
from kubedl_tpu.gang.interface import (
    ANNOTATION_GANG_NAME,
    CapacityDirector,
    GangScheduler,
    GangSnapshot,
)
from kubedl_tpu.utils.tenancy import get_tenancy
from kubedl_tpu.analysis.witness import new_rlock

log = logging.getLogger(__name__)


@dataclass
class PodGroupSpec:
    min_member: int = 0
    tpu_chips: int = 0
    tpu_slice: str = ""
    num_slices: int = 1


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Reserved
    slice_name: str = ""  # first reserved slice (printer column)
    slice_names: List[str] = field(default_factory=list)


@dataclass
class PodGroup:
    # podgroups CRD declares `subresources: status: {}` — phase/slice
    # writes must go through the store's update_status().
    STATUS_SUBRESOURCE = True

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)
    kind: str = "PodGroup"


@dataclass
class _GangState:
    min_member: int = 0
    tpu_chips: int = 0
    requested_slice: str = ""
    # reserved slices, ordered by slice-id; empty = waiting. A gang asks
    # for num_slices whole slices (multislice JAXJob spans several slices
    # over DCN) and gets all of them or none.
    slice_names: List[str] = field(default_factory=list)
    num_slices: int = 1
    total_member: int = 0  # total replicas (min_member can be lower)
    priority: int = 0
    seq: int = 0  # admission order for FIFO tie-break
    # owning job kind: gang keys are ns/name (reference parity — kube-batch
    # PodGroups are named after the job), so deletion paths must verify the
    # kind to avoid releasing a same-named other-kind job's gang
    kind: str = ""
    # -- capacity-scheduler state (sched/capacity.py) -------------------
    # from the kubedl.io/tenancy annotation; unannotated jobs pool under
    # "default" (sched/quota.py normalize_tenant)
    tenant: str = "default"
    # elastic: ordered admissible shapes, preferred first (requested_slice
    # is the CURRENT target and may be resized among these by directive)
    admissible_slices: List[str] = field(default_factory=list)
    # heterogeneous MPMD pipeline gang (JAXJob spec.pipeline.stageSlices,
    # len == num_slices): slice i of the reservation is STAGE i's and
    # must match stage_slices[i]; empty = homogeneous (requested_slice)
    stage_slices: List[str] = field(default_factory=list)
    # mixed-role RL gang (JAXJob spec.rl): roles[i] labels slice i
    # ("actor" | "learner"); the per-role shapes ride stage_slices so
    # the actor and learner gangs admit as one all-or-nothing unit
    roles: List[str] = field(default_factory=list)
    hold_until: float = 0.0  # monotonic; preemption backoff — no reserving before
    preemptions: int = 0  # times this gang was evicted by directive
    waiting_since: float = 0.0  # monotonic; when the gang last lost/lacked slices
    granted_at: float = 0.0  # monotonic; when the current reservation was made
    live_reshard: bool = False  # spec.elastic.liveReshard opt-in
    quiesce_s: float = 0.0  # spec.elastic.quiesceTimeoutS (0 = unset)

    def held(self, now: Optional[float] = None) -> bool:
        return self.hold_until > (time.monotonic() if now is None else now)

    @property
    def slice_name(self) -> Optional[str]:
        return self.slice_names[0] if self.slice_names else None


@dataclass
class _Drain:
    """Slices released by evict_gang but NOT yet grantable: the victim's
    pods may still be inside the executor's SIGTERM grace, checkpointing.
    The slices free when every tracked pod is confirmed gone (the
    executor calls release() AFTER the grace window closes) or when the
    deadline passes (safety valve for executors that never confirm —
    e.g. real-kubelet mode, where the kubelet owns the grace)."""

    slices: List[str] = field(default_factory=list)
    # pod keys awaiting confirmation; None = unknown (the pod listing
    # failed at evict time) — then ONLY the deadline frees the slices
    pods: Optional[set] = None
    deadline: float = 0.0  # monotonic


class TPUSliceAdmitter(GangScheduler):
    """Pool of TPU slices + an unlimited local CPU 'node'."""

    name = "tpu-slice"

    def __init__(
        self,
        store: ObjectStore,
        slices: Optional[List[SliceInfo]] = None,
        drain_timeout: float = 30.0,
    ) -> None:
        self.store = store
        self._lock = new_rlock("gang.slice_admitter.TPUSliceAdmitter._lock")
        self._slices: Dict[str, SliceInfo] = {s.name: s for s in (slices or [])}
        self._gangs: Dict[str, _GangState] = {}
        # implicit single-pod reservations: pod key -> slice name
        self._solo: Dict[str, str] = {}
        self._seq = 0  # monotonic gang admission counter
        # optional capacity director (sched/capacity.py): owns the
        # waiting-gang policy; None keeps the built-in (priority, FIFO)
        self._director: Optional[CapacityDirector] = None
        # eviction drain phase: gang key -> slices held back until the
        # victim's pods confirm exit (see evict_gang / release)
        self._drains: Dict[str, _Drain] = {}
        self.drain_timeout = drain_timeout
        # slices reported dead (slice_failed): never re-granted; dropped
        # from the pool once their drain completes — the chips release
        # exactly once, through the same accounting as an eviction
        self._dead: set = set()
        # flight recorder (obs/trace.py Tracer), wired by the operator:
        # each grant retro-records the gang's queue wait as a span, so
        # the goodput accountant can tell scheduling delay (and, via
        # cause=requeue, preemption downtime) from training time.
        # Grants happen under the admitter lock, but the span's file
        # write must not: records queue here and drain at the public
        # entry points — a slow trace volume must never stall scheduling.
        self.tracer = None
        self._span_queue: List = []
        # write-ahead grant/drain journal (kubedl_tpu/journal/wal.py),
        # wired by the operator AFTER restore_from_journal: every
        # transition below appends durably BEFORE its in-memory commit
        self._journal = None
        # pod keys whose pods_start is already journaled (dedup: the
        # executor re-polls placements; replay rebuilds this set)
        self._journal_started: set = set()
        # group commit (docs/control_plane_scale.md): seq of the last
        # journal record this admitter wrote; _journal_sync() blocks
        # until an fsync covers it, at every public entry point, after
        # the lock drops and before any effect escapes
        self._journal_last_seq = 0
        # -- incremental demand view (docs/control_plane_scale.md) ------
        # every scheduling-relevant mutation bumps _rev; per-gang deltas
        # accumulate in _changed (drained by demand_changes, single
        # consumer: the capacity scheduler's IncrementalDemandView)
        self._rev = 0
        self._changed: set = set()
        self._pool_changed = False
        # waiting-gang index: keys of gangs with TPU demand and no
        # reservation — the reservation pass scans THIS, not every gang
        self._waiting: set = set()

    @staticmethod
    def _drain_marker(gang_key: str) -> str:
        return f"drain:{gang_key}"

    # ------------------------------------------------------------------
    # write-ahead journal (docs/ha.md)
    # ------------------------------------------------------------------

    def attach_journal(self, journal) -> None:
        """Start journaling transitions (without replay — tests and the
        journal-off bench lane; the operator uses restore_from_journal,
        which attaches after replaying)."""
        with self._lock:
            self._journal = journal

    def _journal_op(self, op: str, gang: str = "", **data) -> None:
        """Journal write BEFORE the in-memory commit — called under the
        admitter lock at each transition choke point, so journal order
        always equals commit order and a crash between the write and the
        commit leaves the journal at most one record AHEAD of memory,
        which replay applies safely.  A StaleEpochError (deposed leader)
        propagates: the mutation the caller was about to make must NOT
        happen.  The write is flushed but not yet fsync'd: the public
        entry point that triggered it calls _journal_sync() after the
        lock drops, BEFORE any effect of the transition escapes — which
        lets concurrent entry points share one group-commit fsync
        instead of serializing the disk inside the lock."""
        if self._journal is not None:
            rec = self._journal.append_nosync(op, gang=gang, **data)
            self._journal_last_seq = int(rec["seq"])

    def _journal_sync(self) -> None:
        """Group-commit barrier (docs/control_plane_scale.md): block
        until an fsync covers the last record this admitter wrote.
        Called by every public mutating entry point AFTER the admitter
        lock drops and BEFORE any effect externalizes (a placement
        returned to the executor, a PodGroup mirror written, a caller
        proceeding to pod deletion) — so no transition is observable
        before its record is durable, which is the write-ahead contract
        the model checker's journaled machines assume."""
        j = self._journal
        if j is not None:
            j.sync_to(self._journal_last_seq)

    # -- incremental demand view marks (under the lock) -----------------

    def _note_change(self, key: str) -> None:
        """Mark a gang's scheduling state changed (grant, evict, resize,
        create, delete) and maintain the waiting index."""
        self._rev += 1
        self._changed.add(key)
        state = self._gangs.get(key)
        if state is not None and state.tpu_chips > 0 and not state.slice_names:
            self._waiting.add(key)
        else:
            self._waiting.discard(key)

    def _note_pool(self) -> None:
        """Pool membership or shape changed (set_pool, slice death) —
        the view consumer must rebuild from scratch."""
        self._rev += 1
        self._pool_changed = True

    def _note_avail(self) -> None:
        """Slice availability changed without any gang's own state
        changing (a drain freed, a solo pod came or went): no per-gang
        delta, but a scheduler tick must not skip on an unchanged rev."""
        self._rev += 1

    def demand_rev(self) -> int:
        """Monotonic change counter: unchanged rev == no scheduling-
        relevant admitter transition since (tick-skip check)."""
        with self._lock:
            return self._rev

    def demand_changes(self, since_rev: int):
        """Single-consumer delta feed for the incremental demand view:
        (rev, {gang key: GangSnapshot or None}, pool_changed) covering
        every gang whose scheduling state changed since the last drain
        (None = gang deleted); clears the marks.  pool_changed means
        slice membership/shape changed — rebuild from gang_snapshots().
        """
        with self._lock:
            if (since_rev == self._rev and not self._changed
                    and not self._pool_changed):
                return self._rev, {}, False
            delta = {}
            for key in self._changed:
                state = self._gangs.get(key)
                delta[key] = (None if state is None
                              else self._snapshot(key, state))
            self._changed.clear()
            pool_changed = self._pool_changed
            self._pool_changed = False
            return self._rev, delta, pool_changed

    @staticmethod
    def _gang_meta(state: _GangState) -> Dict:
        """The _GangState snapshot a grant record carries so replay can
        rebuild the gang without waiting for the job to re-reconcile."""
        return {
            "min_member": state.min_member,
            "tpu_chips": state.tpu_chips,
            "requested_slice": state.requested_slice,
            "num_slices": state.num_slices,
            "total_member": state.total_member,
            "priority": state.priority,
            "kind": state.kind,
            "tenant": state.tenant,
            "admissible_slices": list(state.admissible_slices),
            "stage_slices": list(state.stage_slices),
            "roles": list(state.roles),
            "live_reshard": state.live_reshard,
            "quiesce_s": state.quiesce_s,
        }

    def _state_from_meta(self, meta: Dict) -> _GangState:
        self._seq += 1
        return _GangState(
            min_member=int(meta.get("min_member", 0)),
            tpu_chips=int(meta.get("tpu_chips", 0)),
            requested_slice=str(meta.get("requested_slice", "")),
            num_slices=max(int(meta.get("num_slices", 1) or 1), 1),
            total_member=int(meta.get("total_member", 0)),
            priority=int(meta.get("priority", 0)),
            seq=self._seq,
            kind=str(meta.get("kind", "")),
            tenant=str(meta.get("tenant", "") or "default"),
            admissible_slices=[str(s) for s in meta.get(
                "admissible_slices", [])],
            stage_slices=[str(s) for s in meta.get("stage_slices", [])],
            roles=[str(r) for r in meta.get("roles", [])],
            waiting_since=time.monotonic(),
            live_reshard=bool(meta.get("live_reshard", False)),
            quiesce_s=float(meta.get("quiesce_s", 0.0) or 0.0),
        )

    def restore_from_journal(self, journal) -> Dict[str, int]:
        """Replay the journal against the observed pod set and attach
        it (the operator calls this once, on startup, BEFORE the
        executor starts assigning).  Fold the records into an effective
        state (grants, drains, dead slices, started pods), then
        reconcile against the CURRENT pool: a grant whose slice is
        missing, already claimed, or journaled dead resolves
        CONSERVATIVELY — the whole reservation is withheld
        (all-or-nothing), still-free slices park as a deadline-only
        drain, and the gang returns to waiting.  Never re-grant over a
        live pod."""
        records = journal.open()
        grants: Dict[str, Dict] = {}
        drains: Dict[str, Dict] = {}
        dead: set = set()
        started: set = set()
        for rec in records:
            op = rec.get("op")
            gang = rec.get("gang", "")
            data = rec.get("data", {}) or {}
            if op == "grant":
                grants[gang] = {
                    "slices": [str(s) for s in data.get("slices", [])],
                    "meta": data.get("state", {}) or {},
                }
            elif op == "pods_start":
                pod = data.get("pod")
                if pod:
                    started.add(str(pod))
            elif op == "evict":
                prev = grants.pop(gang, None)
                if data.get("drain", True):
                    d = drains.get(gang)
                    fresh = d is None
                    if fresh:
                        d = drains[gang] = {"slices": [], "pods": None}
                    for s in data.get("slices", []):
                        if s not in d["slices"]:
                            d["slices"].append(str(s))
                    pods = data.get("pods")
                    new_pods = (None if pods is None
                                else {str(p) for p in pods})
                    # merge mirrors evict_gang: unknown wins
                    # (deadline-only) once either side is unknown
                    if fresh:
                        d["pods"] = new_pods
                    elif d["pods"] is None or new_pods is None:
                        d["pods"] = None
                    else:
                        d["pods"] |= new_pods
                grow = data.get("grow") or []
                if grow:
                    meta = dict((prev or {}).get(
                        "meta", data.get("state", {}) or {}))
                    if data.get("resize_to"):
                        meta["requested_slice"] = str(data["resize_to"])
                    grants[gang] = {
                        "slices": [str(s) for s in grow], "meta": meta}
            elif op == "release":
                d = drains.get(gang)
                pod = data.get("pod")
                if d is not None and d["pods"] is not None and pod:
                    d["pods"].discard(str(pod))
                started.discard(str(pod or ""))
            elif op in ("confirm_drain", "drain_timeout"):
                drains.pop(gang, None)
            elif op == "slice_failed":
                sname = str(data.get("slice", ""))
                if sname:
                    dead.add(sname)
                if gang and gang in grants:
                    grants.pop(gang)
                    d = drains.setdefault(
                        gang, {"slices": [], "pods": None})
                    if sname and sname not in d["slices"]:
                        d["slices"].append(sname)
                    d["pods"] = None  # deadline-only, like the live op
            elif op == "delete_gang":
                grants.pop(gang, None)
        conflicts = 0
        restored = 0
        with self._lock:
            deadline = time.monotonic() + self.drain_timeout
            for gang_key, g in sorted(grants.items()):
                slices = g["slices"]
                bad = [
                    s for s in slices
                    if s not in self._slices or s in dead
                    or self._slices[s].reserved_by is not None
                ]
                if bad or not slices:
                    # pool changed / double claim / dead slice under a
                    # journaled grant: withhold the whole reservation
                    conflicts += 1
                    log.warning(
                        "journal replay: grant for %s conflicts with "
                        "reality on %s — parking as drain, gang back "
                        "to waiting", gang_key, bad)
                    marker = self._drain_marker(gang_key)
                    parked = []
                    for s in slices:
                        info = self._slices.get(s)
                        if info is not None and info.reserved_by is None:
                            info.reserved_by = marker
                            parked.append(s)
                    if parked:
                        self._drains[gang_key] = _Drain(
                            slices=parked, pods=None, deadline=deadline)
                        self._dead.update(
                            s for s in parked if s in dead)
                    continue
                for s in slices:
                    self._slices[s].reserved_by = gang_key
                state = self._state_from_meta(g["meta"])
                state.slice_names = list(slices)
                state.granted_at = time.monotonic()
                self._gangs[gang_key] = state
                restored += 1
            for gang_key, d in sorted(drains.items()):
                marker = self._drain_marker(gang_key)
                parked = []
                for s in d["slices"]:
                    info = self._slices.get(s)
                    if info is not None and info.reserved_by is None:
                        info.reserved_by = marker
                        parked.append(s)
                if parked:
                    self._drains[gang_key] = _Drain(
                        slices=parked,
                        pods=(set(d["pods"])
                              if d["pods"] is not None else None),
                        deadline=deadline)
                    self._dead.update(s for s in parked if s in dead)
            # a journaled-dead slice that came back free in the pool
            # listing: drop it — the inventory owns resurrection
            for s in dead:
                info = self._slices.get(s)
                if info is not None and info.reserved_by is None:
                    del self._slices[s]
            self._journal_started = started
            for key in self._gangs:
                self._changed.add(key)
            self._note_pool()  # replay reshaped everything: full rebuild
        # observed-pod cross-check (store listing OUTSIDE the lock): a
        # live pod whose gang the journal shows as gone means records
        # and reality disagree — count it loudly; the reconcile loop
        # deletes such pods, and their slices (if any were restored)
        # are already parked or reserved, never free-for-grant.
        covered = set(grants) | set(drains)
        try:
            pods = self.store.list("Pod")
        except Exception:  # noqa: BLE001 — store racing startup
            pods = []
        for pod in pods:
            gk = pod.metadata.annotations.get(ANNOTATION_GANG_NAME)
            if gk and gk not in covered:
                conflicts += 1
                log.warning(
                    "journal replay: live pod %s/%s belongs to gang %s "
                    "with no journaled grant or drain",
                    pod.metadata.namespace, pod.metadata.name, gk)
        journal.note_replay(len(records), conflicts)
        with self._lock:
            self._journal = journal
        return {"records": len(records), "conflicts": conflicts,
                "gangs": restored}

    def set_director(self, director: Optional[CapacityDirector]) -> None:
        """Attach/detach the capacity scheduler's policy hooks."""
        with self._lock:
            self._director = director

    @classmethod
    def with_pool(cls, store: ObjectStore, slice_types: List[str]) -> "TPUSliceAdmitter":
        infos = []
        for i, name in enumerate(slice_types):
            st = parse_slice_type(name)
            infos.append(SliceInfo(name=f"slice-{i}-{st.name}", type=st))
        return cls(store, infos)

    def set_pool(self, infos: List[SliceInfo]) -> None:
        """Replace the slice pool (node-inventory updates, k8s/nodes.py).
        Reservations carry over by slice name; gangs whose slice vanished
        OR changed shape go back to waiting and re-reserve. Affected
        PodGroup mirrors are re-written so dashboards never show a
        reservation on hardware that no longer exists."""
        with self._lock:
            old = self._slices
            new: Dict[str, SliceInfo] = {}
            # slice names whose reservation did NOT carry over (gone, or
            # the node pool was re-provisioned with a different shape)
            invalidated = set(old)
            for info in infos:
                prev = old.get(info.name)
                if prev is not None and prev.type == info.type:
                    info.reserved_by = prev.reserved_by
                    invalidated.discard(info.name)
                new[info.name] = info
            self._slices = new
            self._note_pool()
            changed_keys = []
            for key, state in self._gangs.items():
                if state.slice_names and any(
                    s not in new or s in invalidated for s in state.slice_names
                ):
                    # all-or-nothing holds for revocation too: losing any
                    # slice of a multislice gang frees the survivors and
                    # sends the whole gang back to waiting
                    for s in state.slice_names:
                        info = new.get(s)
                        if info is not None and info.reserved_by == key:
                            info.reserved_by = None
                    state.slice_names = []
                    state.waiting_since = time.monotonic()
                    changed_keys.append(key)
                    self._note_change(key)
            self._solo = {
                pod_key: sname for pod_key, sname in self._solo.items()
                if sname in new and sname not in invalidated
            }
            # a re-provisioned pool supersedes stale death reports
            self._dead &= set(new) - invalidated
            # drains only track slices that still exist in the pool; a
            # drain whose every slice vanished has nothing left to hold
            for gk in list(self._drains):
                drain = self._drains[gk]
                drain.slices = [
                    s for s in drain.slices
                    if s in new and s not in invalidated
                ]
                if not drain.slices:
                    del self._drains[gk]
            changed_keys.extend(self._reserve_waiting())
        self._journal_sync()
        for key in changed_keys:
            self._remirror_podgroup_status(key)
        self._drain_spans()

    def _remirror_podgroup_status(self, gang_key: str) -> None:
        """Refresh the PodGroup mirror's status after a pool-driven
        reservation change (no job reconcile fires for those)."""
        namespace, _, name = gang_key.partition("/")
        with self._lock:
            state = self._gangs.get(gang_key)
            if state is None:
                return
            phase = "Reserved" if state.slice_names else "Pending"
            slice_name = state.slice_name or ""
            slice_names = list(state.slice_names)
        try:
            # the no-change check may serve from the informer cache; a
            # WRITE needs the fresh resourceVersion (a cached rv makes
            # the swallowed Conflict below permanent — pool changes get
            # no follow-up reconcile to retry)
            pg = self.store.get("PodGroup", namespace, name)
            if (pg.status.phase, pg.status.slice_names) == (phase, slice_names):
                return
            pg = read_fresh(self.store, "PodGroup", namespace, name)
        except NotFound:
            return
        if (pg.status.phase, pg.status.slice_names) == (phase, slice_names):
            return
        pg.status.phase = phase
        pg.status.slice_name = slice_name
        pg.status.slice_names = slice_names
        try:
            write_status(self.store, pg)
        except (Conflict, NotFound):
            pass  # next mirror pass converges

    # ------------------------------------------------------------------
    # GangScheduler contract
    # ------------------------------------------------------------------

    def create_gang(self, job, replicas: Dict[str, ReplicaSpec]):
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            state = self._gangs.get(key)
            if state is None:
                total = sum(int(s.replicas or 0) for s in replicas.values())
                sched = (job.spec.run_policy.scheduling_policy
                         if getattr(job.spec, "run_policy", None) else None)
                min_member = total
                requested_slice = ""
                priority = 0
                admissible: List[str] = []
                if sched is not None:
                    # Honor MinAvailable (the reference ignored it).
                    if sched.min_available:
                        min_member = min(sched.min_available, total)
                    requested_slice = sched.tpu_slice
                    priority = int(sched.priority or 0)
                    if requested_slice:
                        # elastic: preferred shape first, then declared
                        # fallbacks (unparseable entries are dropped —
                        # workload validation reports them to the user)
                        admissible = [requested_slice]
                        for alt in getattr(sched, "tpu_slice_fallbacks", None) or []:
                            try:
                                parse_slice_type(alt)
                            except ValueError:
                                continue
                            if alt not in admissible:
                                admissible.append(alt)
                try:
                    tenancy = get_tenancy(job)
                except ValueError:
                    tenancy = None  # malformed annotation: pooled tenant
                chips = sum(
                    int(s.replicas or 0) * s.template.spec.tpu_chips()
                    for s in replicas.values()
                )
                num_slices = max(int(getattr(job.spec, "num_slices", 1) or 1), 1)
                elastic = getattr(job.spec, "elastic", None)
                # heterogeneous MPMD pipeline gang: per-stage slice
                # shapes (validated at submit — unparseable/ragged lists
                # are dropped here so the admitter never wedges on them)
                pipe = getattr(job.spec, "pipeline", None)
                stage_slices: List[str] = []
                roles: List[str] = []
                if (pipe is not None and getattr(pipe, "mpmd", False)
                        and getattr(pipe, "stage_slices", None)):
                    cand = [str(s) for s in pipe.stage_slices]
                    try:
                        for s in cand:
                            parse_slice_type(s)
                        if len(cand) == num_slices:
                            stage_slices = cand
                    except ValueError:
                        stage_slices = []
                # mixed-ROLE RL gang (JAXJob spec.rl): per-role shapes
                # ride the same hetero machinery as stageSlices — one
                # distinct slice per entry, STAGE-ordered (actors first,
                # matching the pod slice-id labels), all-or-nothing: an
                # actor fleet without a learner slice reserves NOTHING
                # (a feasible-but-blocked fleet still shields its
                # matching slices; an infeasible one shields nothing).
                # Validated at submit; unparseable or ragged specs are
                # dropped here so the admitter never wedges on them
                rl = getattr(job.spec, "rl", None)
                if (rl is not None and getattr(rl, "actor_slice", "")
                        and getattr(rl, "learner_slice", "")):
                    n_act = int(getattr(rl, "actor_replicas", 0) or 0)
                    n_lrn = int(getattr(rl, "learner_replicas", 0) or 0)
                    cand = ([str(rl.actor_slice)] * n_act
                            + [str(rl.learner_slice)] * n_lrn)
                    try:
                        for s in cand:
                            parse_slice_type(s)
                        if cand and len(cand) == num_slices:
                            stage_slices = cand
                            roles = (["actor"] * n_act
                                     + ["learner"] * n_lrn)
                    except ValueError:
                        pass
                self._seq += 1
                state = _GangState(
                    min_member=min_member, tpu_chips=chips,
                    requested_slice=requested_slice,
                    num_slices=num_slices, total_member=total,
                    priority=priority, seq=self._seq,
                    kind=getattr(job, "kind", "") or "",
                    tenant=(tenancy.tenant if tenancy else "") or "default",
                    admissible_slices=admissible,
                    stage_slices=stage_slices,
                    roles=roles,
                    waiting_since=time.monotonic(),
                    live_reshard=bool(getattr(elastic, "live_reshard", False)),
                    quiesce_s=float(
                        getattr(elastic, "quiesce_timeout_s", 0.0) or 0.0),
                )
                self._gangs[key] = state
                self._note_change(key)
            self._reserve_waiting()
        self._journal_sync()
        self._drain_spans()
        self._mirror_podgroup(job, state)
        return state

    def bind_pod_to_gang(self, job, pod) -> None:
        pod.metadata.annotations[ANNOTATION_GANG_NAME] = (
            f"{job.metadata.namespace}/{job.metadata.name}"
        )
        pod.spec.scheduler_name = self.name

    def get_gang(self, namespace: str, name: str):
        with self._lock:
            return self._gangs.get(f"{namespace}/{name}")

    def delete_gang(self, job, expected_kind: str = "") -> None:
        """Release the job's gang. `expected_kind` (when set) makes the
        pop conditional UNDER THE LOCK: gang keys are ns/name (reference
        parity — kube-batch PodGroups are named after the job), so a
        deletion path racing a same-named job of another kind must not
        release the live record a check-then-act outside the lock could."""
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            state = self._gangs.get(key)
            if state is not None and expected_kind and state.kind not in (
                "", expected_kind
            ):
                return  # another kind's live gang took the key — not ours
            if state is not None:
                # write-AHEAD: the gang (and its reservation) is gone
                # durably before the slices free
                self._journal_op(
                    "delete_gang", gang=key,
                    slices=list(state.slice_names))
            self._gangs.pop(key, None)
            if state:
                for sname in state.slice_names:
                    info = self._slices.get(sname)
                    if info and info.reserved_by == key:
                        info.reserved_by = None
                self._note_change(key)
        self._journal_sync()
        try:
            self.store.delete("PodGroup", job.metadata.namespace, job.metadata.name)
        except NotFound:
            pass

    # ------------------------------------------------------------------
    # Executor scheduler protocol
    # ------------------------------------------------------------------

    def assign(self, pod) -> Optional[Placement]:
        placement = self._assign(pod)
        # grant/pods_start records durable BEFORE the placement escapes
        self._journal_sync()
        self._drain_spans()  # a poll that granted exports its span now
        return placement

    def _assign(self, pod) -> Optional[Placement]:
        chips = pod.spec.tpu_chips()
        gang_key = pod.metadata.annotations.get(ANNOTATION_GANG_NAME)
        if gang_key is None:
            if chips <= 0:
                return Placement(node_name="local-cpu")
            return self._assign_solo(pod, chips)
        with self._lock:
            state = self._gangs.get(gang_key)
            if state is None:
                return None  # gang not created yet; stay Pending
            if state.tpu_chips <= 0:
                return Placement(node_name="local-cpu")
            if not state.slice_names:
                self._reserve_waiting()
            if not state.slice_names:
                return None  # no slices free (or higher-priority gangs ahead)
            # multislice: the pod's slice-id label picks which reserved
            # slice it lands on (workloads/jaxjob.py stamps contiguous
            # worker groups); single-slice gangs have exactly one entry
            try:
                slice_idx = int(pod.metadata.labels.get(LABEL_SLICE_ID, "0"))
            except ValueError:
                slice_idx = 0
            if not (0 <= slice_idx < len(state.slice_names)):
                return None  # label out of range for the reservation
            info = self._slices[state.slice_names[slice_idx]]
            pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            if pod_key not in self._journal_started:
                # write-AHEAD: pods_start, once per pod (the executor
                # re-polls placements) — after a crash, replay knows a
                # live process may be on this slice even before the pod
                # listing says so
                self._journal_op(
                    "pods_start", gang=gang_key, pod=pod_key,
                    slice=info.name)
                self._journal_started.add(pod_key)
            return self._place_on_slice(pod, info, gang=state)

    def release(self, pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        gang_key = pod.metadata.annotations.get(ANNOTATION_GANG_NAME)
        changed: List[str] = []
        with self._lock:
            slice_name = self._solo.pop(key, None)
            if slice_name:
                info = self._slices.get(slice_name)
                if info and info.reserved_by == key:
                    if slice_name in self._dead:
                        self._dead.discard(slice_name)
                        del self._slices[slice_name]
                    else:
                        info.reserved_by = None
            # drain confirmation: the executor calls release() only
            # AFTER the pod's processes exited (SIGTERM grace included),
            # so the last confirmation proves the victim stopped
            # touching its slices — now they may free and re-grant
            drain = self._drains.get(gang_key) if gang_key else None
            if drain is not None and drain.pods is not None:
                if key in drain.pods:
                    # write-AHEAD: the exit confirmation is durable
                    # before the tracked set shrinks (the LAST one
                    # enables confirm_drain, which journals itself)
                    self._journal_op("release", gang=gang_key, pod=key)
                drain.pods.discard(key)
                if not drain.pods:
                    changed = self._finish_drain(gang_key)
            self._journal_started.discard(key)
            if slice_name:
                self._note_avail()  # a solo reservation freed
        self._journal_sync()
        for k in changed:
            self._remirror_podgroup_status(k)
        self._drain_spans()
        # Gang reservations outlive individual pods (restarts keep the
        # slice); they free on delete_gang.

    def _free_drained_slice(self, sname: str, marker: str) -> None:
        """Free one drained slice (under the lock). A slice reported DEAD
        leaves the pool here instead of freeing — its chips release
        exactly once, through this single choke point."""
        info = self._slices.get(sname)
        if info is None or info.reserved_by != marker:
            return
        if sname in self._dead:
            self._dead.discard(sname)
            del self._slices[sname]
            self._note_pool()  # a dead slice left the pool
        else:
            info.reserved_by = None
            self._note_avail()

    def _finish_drain(self, gang_key: str) -> List[str]:
        """Free a completed drain's slices (under the lock) and run a
        reservation pass — the successor takes over only now. Returns
        the keys of gangs granted in that pass."""
        drain = self._drains.get(gang_key)
        if drain is None:
            return []
        # write-AHEAD: the drain completes durably before its slices
        # free — replay must not re-park slices a successor now holds
        self._journal_op(
            "confirm_drain", gang=gang_key, slices=list(drain.slices))
        self._drains.pop(gang_key)
        marker = self._drain_marker(gang_key)
        for sname in drain.slices:
            self._free_drained_slice(sname, marker)
        return self._reserve_waiting()

    def _expire_drains(self, now: float) -> None:
        """Free the slices of drains whose deadline passed (under the
        lock; no follow-up pass — callers run one). The safety valve
        for modes where nobody calls release() per pod (real-kubelet
        backends own the grace window themselves)."""
        for gk in [k for k, d in self._drains.items() if d.deadline <= now]:
            drain = self._drains[gk]
            # write-AHEAD: grace expiry is a real transition too —
            # without it replay would resurrect a finished drain
            self._journal_op(
                "drain_timeout", gang=gk, slices=list(drain.slices))
            self._drains.pop(gk)
            marker = self._drain_marker(gk)
            for sname in drain.slices:
                self._free_drained_slice(sname, marker)

    def confirm_drain(self, gang_key: str) -> None:
        """Finish a gang's drain early: the capacity scheduler calls this
        when a live reshard's replies prove the gang is running on its NEW
        slices — the old ones can free without waiting for pod exits that
        will never come (the pods did not restart)."""
        with self._lock:
            changed = self._finish_drain(gang_key)
        self._journal_sync()
        for k in changed:
            self._remirror_podgroup_status(k)
        self._drain_spans()

    def slice_failed(self, slice_name: str) -> Optional[str]:
        """Executor/inventory report: a pool slice died mid-run. The dead
        slice's chips release ONLY ONCE, through the eviction drain
        accounting: the slice parks as `drain:<owner>` (deadline-only —
        live-resharding pods never exit, so pod confirmations cannot
        close it) and leaves the pool when the drain completes. The owning
        gang loses its ENTIRE reservation (all-or-nothing holds for
        revocation) and goes back to waiting; the capacity scheduler then
        offers a live shrink to a declared fallback shape instead of
        whole-gang eviction. Returns the owning gang key (None for free /
        solo / unknown slices)."""
        changed: List[str] = []
        gang_key: Optional[str] = None
        with self._lock:
            info = self._slices.get(slice_name)
            if info is None:
                return None
            owner = info.reserved_by
            # write-AHEAD: the death is durable before any revocation —
            # replay marks the slice dead and (for a gang owner) parks
            # it while freeing the survivors, like the branches below
            self._journal_op(
                "slice_failed",
                gang=(owner if isinstance(owner, str)
                      and owner in self._gangs else ""),
                slice=slice_name)
            if owner is None:
                # free slice died: nothing drains, drop it now
                del self._slices[slice_name]
                self._dead.discard(slice_name)
                self._note_pool()
            elif isinstance(owner, str) and owner.startswith("drain:"):
                # already draining (eviction in flight): just mark dead so
                # the drain completion drops it instead of re-granting
                self._dead.add(slice_name)
            elif owner in self._gangs:
                gang_key = owner
                state = self._gangs[owner]
                self._dead.add(slice_name)
                info.reserved_by = self._drain_marker(owner)
                drain = self._drains.get(owner)
                deadline = time.monotonic() + self.drain_timeout
                if drain is None:
                    self._drains[owner] = _Drain(
                        slices=[slice_name], pods=None, deadline=deadline)
                else:
                    if slice_name not in drain.slices:
                        drain.slices.append(slice_name)
                    drain.pods = None  # deadline-only: pods stay alive
                    drain.deadline = max(drain.deadline, deadline)
                # all-or-nothing: survivors free, the gang re-reserves as
                # a whole (possibly at a fallback shape)
                for sname in state.slice_names:
                    if sname == slice_name:
                        continue
                    surv = self._slices.get(sname)
                    if surv is not None and surv.reserved_by == owner:
                        surv.reserved_by = None
                state.slice_names = []
                state.waiting_since = time.monotonic()
                changed.append(owner)
                self._note_change(owner)
            else:
                # solo-pod reservation: mark dead; release() drops it when
                # the pod goes away (deadline-free — the pod owns no gang)
                self._dead.add(slice_name)
                self._note_avail()
            changed.extend(self._reserve_waiting())
        self._journal_sync()
        for k in changed:
            self._remirror_podgroup_status(k)
        self._drain_spans()
        return gang_key

    def draining(self) -> Dict[str, List[str]]:
        """Gang key -> slice names still in the eviction drain phase
        (observability: CLI queue view, tests)."""
        with self._lock:
            return {k: list(d.slices) for k, d in self._drains.items()}

    def _gang_pod_keys(self, gang_key: str) -> Optional[List[str]]:
        """Keys of the gang's live pods (store listing, done OUTSIDE the
        admitter lock) — the set whose exit confirmations complete an
        eviction drain. Same owner-kind guard as the capacity
        scheduler's pod deletion: gang keys are ns/name, so a same-named
        job of another kind carries the identical annotation. Returns
        None when the listing FAILS — the caller must fail closed
        (deadline-only drain), not treat the error as "no pods"."""
        with self._lock:
            state = self._gangs.get(gang_key)
            if state is None or not state.slice_names:
                return []
            kind = state.kind
        namespace = gang_key.partition("/")[0]
        try:
            pods = self.store.list("Pod", namespace=namespace)
        except Exception:  # noqa: BLE001 — store racing shutdown
            return None
        keys = []
        for pod in pods:
            if pod.metadata.annotations.get(ANNOTATION_GANG_NAME) != gang_key:
                continue
            ref = pod.metadata.controller_ref()
            if kind and (ref is None or ref.kind != kind):
                continue
            keys.append(f"{pod.metadata.namespace}/{pod.metadata.name}")
        return keys

    def utilization(self) -> Dict:
        """Pool occupancy snapshot (BASELINE.md "slice utilization" gauge)."""
        with self._lock:
            slices = list(self._slices.values())
            total_chips = sum(s.type.chips for s in slices)
            reserved = [s for s in slices if s.reserved_by is not None]
            reserved_chips = sum(s.type.chips for s in reserved)
            return {
                "slices_total": len(slices),
                "slices_reserved": len(reserved),
                "slices_draining": sum(
                    1 for s in reserved
                    if str(s.reserved_by).startswith("drain:")),
                "chips_total": total_chips,
                "chips_reserved": reserved_chips,
                "utilization": (reserved_chips / total_chips) if total_chips else 0.0,
                "slices": [
                    {
                        "name": s.name,
                        "type": s.type.name,
                        "chips": s.type.chips,
                        "reserved_by": s.reserved_by or "",
                    }
                    for s in slices
                ],
            }

    # ------------------------------------------------------------------
    # Capacity-scheduler directives (sched/capacity.py). The admitter
    # executes reserve/evict/resize; the scheduler decides them.
    # ------------------------------------------------------------------

    def kick(self) -> List[str]:
        """Run a reservation pass now (scheduler tick / hold expiry).
        Returns the keys of gangs that obtained a reservation.  Also the
        journal-compaction choke point: the snapshot is built and the
        file truncated UNDER the lock, atomically with the state it
        mirrors — no append can interleave between the two."""
        with self._lock:
            granted = self._reserve_waiting()
            if self._journal is not None and self._journal.should_compact():
                try:
                    self._journal.compact(self._compaction_records())
                except Exception:  # noqa: BLE001 — a failed compaction
                    # (deposed epoch, disk trouble) must never break a
                    # scheduling pass; appends keep the journal correct
                    log.exception("journal compaction failed")
        self._journal_sync()
        for key in granted:
            self._remirror_podgroup_status(key)
        self._drain_spans()
        return granted

    def _compaction_records(self):
        """Effective-state snapshot for GrantJournal.compact, built UNDER
        the admitter lock.  Replay-equivalent to the live state: drains
        first (an ``evict`` record whose ``grow`` field re-grants the
        gang's CURRENT slices when it also holds some — the grow-while-
        draining shape), then plain grants, the started-pod latches, and
        the dead-slice reports.  Waiting gangs are not journaled (same
        as live operation: they re-enter via job reconcile)."""
        recs = []
        for gk, drain in sorted(self._drains.items()):
            state = self._gangs.get(gk)
            recs.append(("evict", gk, {
                "slices": list(drain.slices),
                "drain": True,
                "pods": (sorted(drain.pods)
                         if drain.pods is not None else None),
                "resize_to": "",
                "grow": (list(state.slice_names)
                         if state is not None else []),
                "state": (self._gang_meta(state)
                          if state is not None and state.slice_names
                          else None),
            }))
        for gk, state in sorted(self._gangs.items()):
            if not state.slice_names or gk in self._drains:
                continue
            recs.append(("grant", gk, {
                "slices": list(state.slice_names),
                "state": self._gang_meta(state)}))
        for pod_key in sorted(self._journal_started):
            recs.append(("pods_start", "", {"pod": pod_key}))
        for sname in sorted(self._dead):
            recs.append(("slice_failed", "", {"slice": sname}))
        return recs

    def gang_snapshots(self) -> List[GangSnapshot]:
        """Read-only copies of every gang's scheduling state."""
        with self._lock:
            return [self._snapshot(k, s) for k, s in self._gangs.items()]

    def total_chips(self) -> int:
        """Pool capacity in chips — cheaper than a full utilization()
        snapshot for callers that only need the denominator."""
        with self._lock:
            return sum(s.type.chips for s in self._slices.values())

    def demand_view(
        self,
        namespace: str,
        name: str,
        slice_type: str = "",
        respect_shields: bool = False,
    ) -> Optional[Dict]:
        """How far is this gang from reserving? Returns {needed, free,
        holders} where `free` counts grantable free slices and `holders`
        are (GangSnapshot, matching_count) pairs for running gangs whose
        reserved slices satisfy the demand — the preemption candidates.
        `slice_type` probes an alternative shape (elastic what-if);
        `respect_shields` additionally subtracts free slices held back
        for OTHER waiting gangs, so elastic decisions don't target
        capacity the reservation pass would refuse. The extra
        `draining` field counts matching slices still in an eviction
        drain — capacity already committed to free, so the preemption
        pass must not evict MORE victims while those complete."""
        key = f"{namespace}/{name}"
        with self._lock:
            state = self._gangs.get(key)
            if state is None:
                return None
            probe = state
            if slice_type and slice_type != state.requested_slice:
                probe = _GangState(
                    tpu_chips=state.tpu_chips,
                    requested_slice=slice_type,
                    num_slices=state.num_slices,
                    tenant=state.tenant,  # headroom is per-tenant
                )
            needed = max(state.num_slices, 1)
            usage = None
            total = 0
            if self._director is not None:
                usage, total = self._usage_by_tenant()
                # a RUNNING gang probing another shape (elastic what-if)
                # would release its own slices first — refund them, or
                # the probe under-reports headroom the evict/resize
                # directive would actually have (wedging legal grows)
                own = sum(
                    self._slices[s].type.chips
                    for s in state.slice_names if s in self._slices
                )
                if own:
                    usage[state.tenant] = max(
                        usage.get(state.tenant, 0) - own, 0)
            # grantable, not just matching: a probe that counts slices
            # the grant step would refuse (tenant-cap headroom) makes
            # the scheduler evict/resize toward capacity that isn't there
            free_pool = self._free_slices()
            if respect_shields:
                shields = [
                    s for s in self._waiting_shields(usage, total)
                    if s is not state
                ]
                shielded = self._shielded_slices(shields, usage, total)
                free_pool = [s for s in free_pool if s.name not in shielded]
            free = len(self._grantable_slices(probe, free_pool, usage, total))
            holders = []
            for other_key, other in self._gangs.items():
                if other_key == key or not other.slice_names:
                    continue
                held = [
                    self._slices[s] for s in other.slice_names if s in self._slices
                ]
                matching = len(self._grantable_slices(probe, held, usage, total))
                if matching:
                    holders.append((self._snapshot(other_key, other), matching))
            drain_pool = [
                s for s in self._slices.values()
                if isinstance(s.reserved_by, str)
                and s.reserved_by.startswith("drain:")
            ]
            draining = len(
                self._grantable_slices(probe, drain_pool, usage, total))
            return {"needed": needed, "free": free, "holders": holders,
                    "draining": draining}

    def evict_gang(
        self,
        namespace: str,
        name: str,
        hold_seconds: float = 0.0,
        resize_to: str = "",
    ) -> List[str]:
        """Scheduler directive: release a running gang's slices and send
        it back to waiting. `hold_seconds` paces the requeue (preemption
        backoff — the gang resumes from checkpoint once re-admitted);
        `resize_to` instead re-targets the gang at another of its
        declared admissible shapes (elastic grow/shrink) and only
        proceeds when enough matching slices are free RIGHT NOW, so a
        grow never trades a running job for nothing. Returns the released
        slice names ([] = nothing done). The caller is responsible for
        driving the job's pods through checkpoint-then-kill (deleting
        them; the engine recreates them Pending).

        Drain phase: when the gang has live pods, the released slices
        do NOT free (or re-grant) immediately — they enter a draining
        state (`reserved_by = "drain:<gang>"`) until the executor
        confirms every pod exited (release() after the SIGTERM-grace
        checkpoint) or `drain_timeout` passes. Without the drain, a
        successor gang's pods could start on a slice whose previous
        owner is still checkpointing inside the grace window — a real
        double-booking on hardware (ROADMAP "drain phase" item). A
        grow (`resize_to`) still pre-grants its NEW slices immediately;
        only the OLD slices drain."""
        key = f"{namespace}/{name}"
        drain_pods = self._gang_pod_keys(key)
        with self._lock:
            state = self._gangs.get(key)
            if state is None or not state.slice_names:
                return []
            grow_chosen: List[SliceInfo] = []
            if resize_to:
                if resize_to not in state.admissible_slices:
                    return []
                probe = _GangState(
                    tpu_chips=state.tpu_chips,
                    requested_slice=resize_to,
                    num_slices=state.num_slices,
                    tenant=state.tenant,  # headroom is per-tenant
                )
                # slices held back for feasible waiting gangs are NOT
                # available to a grow — stealing one would starve the
                # queue (or, under priority, trigger an immediate
                # preempt-back churn). Grantable, not just matching: a
                # slice the cap-aware grant step would refuse must not
                # green-light the eviction.
                usage, total = self._usage_by_tenant()
                grow_shields = [
                    s for s in self._waiting_shields(usage, total)
                    if s is not state
                ]
                shielded = self._shielded_slices(grow_shields, usage, total)
                # the gang still holds its old slices here; releasing
                # them refunds its tenant's usage, so headroom must not
                # count them against the grow
                own = sum(
                    self._slices[s].type.chips
                    for s in state.slice_names if s in self._slices
                )
                usage = dict(usage)
                usage[state.tenant] = max(usage.get(state.tenant, 0) - own, 0)
                free = [
                    s for s in self._grantable_slices(
                        probe, self._free_slices(), usage, total)
                    if s.name not in shielded
                ]
                n = max(state.num_slices, 1)
                if len(free) < n:
                    return []  # target shape not actually available
                # choose the target slices from the VERIFIED list now —
                # re-deriving shields after the release (when the refund
                # can widen a same-tenant waiter's headroom) could newly
                # shield the target and leave the gang with nothing
                picked = self._pick_slices(
                    probe, free, n, self._headroom(probe, usage, total))
                if picked is None:
                    return []  # multislice sum outgrows the cap
                grow_chosen = picked
            released = list(state.slice_names)
            # write-AHEAD: one record carries the whole eviction
            # decision — drained slices, tracked pods, and (for a grow)
            # the pre-verified new slices, so replay re-applies it
            # atomically (grow pre-grant included)
            self._journal_op(
                "evict", gang=key, slices=released,
                drain=bool(drain_pods is None or drain_pods),
                pods=(sorted(drain_pods)
                      if drain_pods is not None else None),
                resize_to=resize_to,
                grow=[s.name for s in grow_chosen],
                state=(self._gang_meta(state) if grow_chosen else None))
            if drain_pods is None or drain_pods:
                # hold the slices in draining until every pod confirms
                # exit (or the deadline) — NOT free, NOT re-grantable.
                # drain_pods None = the pod listing FAILED: fail closed
                # (deadline-only drain), never fail open into an
                # immediate re-grant over possibly-live pods.
                marker = self._drain_marker(key)
                for sname in released:
                    info = self._slices.get(sname)
                    if info is not None and info.reserved_by == key:
                        info.reserved_by = marker
                new_pods = None if drain_pods is None else set(drain_pods)
                drain = self._drains.get(key)
                if drain is None:
                    self._drains[key] = _Drain(
                        slices=list(released), pods=new_pods,
                        deadline=time.monotonic() + self.drain_timeout)
                else:
                    # a second eviction while an old drain is pending
                    # (grow then preempt): merge, keep the later deadline
                    drain.slices.extend(
                        s for s in released if s not in drain.slices)
                    if drain.pods is None or new_pods is None:
                        drain.pods = None  # unknown wins: deadline-only
                    else:
                        drain.pods |= new_pods
                    drain.deadline = max(
                        drain.deadline, time.monotonic() + self.drain_timeout)
            else:
                # no live pods to wait for — free immediately
                for sname in released:
                    info = self._slices.get(sname)
                    if info is not None and info.reserved_by == key:
                        info.reserved_by = None
            state.slice_names = []
            state.waiting_since = time.monotonic()
            if resize_to:
                state.requested_slice = resize_to
            else:
                state.preemptions += 1
            state.hold_until = time.monotonic() + max(hold_seconds, 0.0)
            if resize_to:
                # grant the pre-verified target slices to THIS gang
                # before the general pass — otherwise a higher-ranked
                # waiting gang could take them and the grow would have
                # traded a running job for nothing
                for s in grow_chosen:
                    s.reserved_by = key
                state.slice_names = [s.name for s in grow_chosen]
                state.granted_at = time.monotonic()
                self._record_admission(key, state)
            self._note_change(key)
            changed = [key] + self._reserve_waiting()
        self._journal_sync()
        for k in changed:
            self._remirror_podgroup_status(k)
        self._drain_spans()
        return released

    def resize_gang(self, namespace: str, name: str, slice_type: str) -> bool:
        """Scheduler directive: re-target a WAITING gang at another of its
        declared admissible shapes (elastic shrink while queued). Running
        gangs resize through evict_gang(resize_to=...)."""
        key = f"{namespace}/{name}"
        with self._lock:
            state = self._gangs.get(key)
            if (
                state is None
                or state.slice_names
                or slice_type not in state.admissible_slices
                or state.requested_slice == slice_type
            ):
                return False
            state.requested_slice = slice_type
            self._note_change(key)
            changed = [key] + self._reserve_waiting()
        self._journal_sync()
        for k in changed:
            self._remirror_podgroup_status(k)
        self._drain_spans()
        return True

    def _snapshot(self, key: str, state: _GangState) -> GangSnapshot:
        return GangSnapshot(
            key=key,
            kind=state.kind,
            tenant=state.tenant,
            priority=state.priority,
            seq=state.seq,
            tpu_chips=state.tpu_chips,
            num_slices=state.num_slices,
            requested_slice=state.requested_slice,
            admissible_slices=list(state.admissible_slices),
            stage_slices=list(state.stage_slices),
            roles=list(state.roles),
            slice_names=list(state.slice_names),
            reserved_chips=sum(
                self._slices[s].type.chips
                for s in state.slice_names
                if s in self._slices
            ),
            hold_until=state.hold_until,
            preemptions=state.preemptions,
            waiting_since=state.waiting_since,
            granted_at=state.granted_at,
            live_reshard=state.live_reshard,
            quiesce_s=state.quiesce_s,
        )

    def _usage_by_tenant(self) -> "tuple[Dict[str, int], int]":
        """(tenant -> reserved chips, total pool chips) — under the lock."""
        usage: Dict[str, int] = {}
        for state in self._gangs.values():
            if not state.slice_names:
                continue
            chips = sum(
                self._slices[s].type.chips
                for s in state.slice_names
                if s in self._slices
            )
            usage[state.tenant] = usage.get(state.tenant, 0) + chips
        total = sum(s.type.chips for s in self._slices.values())
        return usage, total

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _free_slices(self) -> List[SliceInfo]:
        return [s for s in self._slices.values() if s.reserved_by is None]

    def _reserve_waiting(self) -> List[str]:
        """Grant free slices to waiting gangs in policy order — the
        attached CapacityDirector's when present, else the built-in
        (priority desc, FIFO) — so a freed slice goes to the front of the
        queue, not to whichever gang's executor poll happens to run next.
        Gangs under a preemption hold sit the pass out (and shield
        nothing); gangs a director refuses (tenant cap) are skipped
        without shielding. Returns the keys of gangs that obtained a
        reservation in this pass."""
        now = time.monotonic()
        self._expire_drains(now)
        # the waiting index keeps this O(waiting), not O(all gangs) —
        # at fleet scale almost every gang is running, not waiting
        eligible = [
            (k, s)
            for k, s in ((k, self._gangs.get(k)) for k in self._waiting)
            if s is not None and not s.slice_names
            and s.tpu_chips > 0 and not s.held(now)
        ]
        if not eligible:
            return []
        eligible.sort(key=lambda kv: kv[1].seq)  # admission order
        director = self._director
        usage: Dict[str, int] = {}
        total_chips = 0
        if director is not None:
            usage, total_chips = self._usage_by_tenant()
            key_by_state = {id(s): k for k, s in eligible}
            ordered = [
                (key_by_state[id(s)], s)
                for s in director.order_waiting(
                    [s for _, s in eligible], usage, total_chips
                )
                if id(s) in key_by_state
            ]
        else:
            ordered = sorted(eligible, key=lambda kv: (-kv[1].priority, kv[1].seq))
        granted = []
        shielded: List[_GangState] = []
        for key, state in ordered:
            if director is not None and not director.may_reserve(
                state, usage, total_chips
            ):
                continue  # capped: no reservation, no shield
            self._try_reserve(
                key, state, shielded,
                usage if director is not None else None, total_chips,
            )
            if state.slice_names:
                granted.append(key)
                if director is not None:
                    # keep caps honest within this pass
                    usage[state.tenant] = usage.get(state.tenant, 0) + sum(
                        self._slices[s].type.chips for s in state.slice_names
                    )
            elif self._feasible(state):
                # Anti-starvation shield: a feasible-but-unsatisfied gang
                # (e.g. a multislice gang holding out for N simultaneously
                # free slices) keeps first claim on every slice matching
                # its demand — later gangs may only reserve slices OUTSIDE
                # that set, or a steady stream of small jobs would snatch
                # each freed slice forever (the gang never holds partial
                # reservations). Gangs with disjoint demands (different
                # slice type) still proceed; infeasible gangs (demand
                # exceeds the pool itself) shield nothing.
                shielded.append(state)
        return granted

    def _feasible(self, state: _GangState) -> bool:
        """Could this gang EVER be satisfied by the current pool (counting
        busy slices as eventually freeable)? Gates the anti-starvation
        shield so an impossible request doesn't wedge the queue. A
        heterogeneous gang needs a FULL per-stage assignment to exist,
        not just enough union-matching slices."""
        if state.stage_slices:
            return self._hetero_assign(
                state, list(self._slices.values()), use_director=False
            ) is not None
        return len(self._matching_slices(state, self._slices.values())) >= max(
            state.num_slices, 1
        )

    def _shielded_slices(
        self,
        exclude: Optional[List[_GangState]] = None,
        usage: Optional[Dict[str, int]] = None,
        total_chips: int = 0,
    ):
        """Names of free slices held back for earlier waiting gangs — only
        slices those gangs could actually be GRANTED (a capped gang must
        not shield an oversized slice it can never take). Pass
        `usage`/`total_chips` when a pass already holds them (avoids a
        redundant full-pool walk per call under the lock)."""
        if not exclude:
            return set()
        if usage is None and self._director is not None:
            usage, total_chips = self._usage_by_tenant()
        out = set()
        free = self._free_slices()
        for g in exclude:
            out.update(
                s.name
                for s in self._grantable_slices(g, free, usage, total_chips)
            )
        return out

    def _waiting_shields(
        self,
        usage: Optional[Dict[str, int]] = None,
        total_chips: int = 0,
    ) -> List[_GangState]:
        """Feasible waiting gangs, as seen by the SOLO-pod path: standalone
        pods must not snatch slices a queued gang is holding out for.
        Held (preemption-backoff) gangs shield nothing — they are being
        paced, not starved — and neither do gangs the director refuses
        (tenant cap): a capped gang cannot reserve, so withholding the
        slice from solo pods would just idle capacity. Pass
        `usage`/`total_chips` when already in hand (avoids a redundant
        full-pool walk under the lock)."""
        now = time.monotonic()
        director = self._director
        waiting = [
            s for s in (self._gangs.get(k) for k in self._waiting)
            if s is not None and not s.slice_names and s.tpu_chips > 0
            and not s.held(now)
        ]
        if not waiting:
            return []
        if director is not None and usage is None:
            usage, total_chips = self._usage_by_tenant()
        return [
            s for s in waiting
            if self._feasible(s)
            and (director is None
                 or director.may_reserve(s, usage, total_chips))
        ]

    @staticmethod
    def _stage_matching(shape: str, pool) -> List[SliceInfo]:
        want = parse_slice_type(shape)
        return [
            s for s in pool
            if s.type.generation == want.generation
            and s.type.chips >= want.chips
        ]

    def _matching_slices(self, state: _GangState, pool) -> List[SliceInfo]:
        """Slices that satisfy the gang's PER-SLICE demand (explicit slice
        type, or chips: the job's total divides over its slices; ceil keeps
        ragged specs safe). A heterogeneous gang (stage_slices) matches the
        UNION of its per-stage shapes — probes and shields count every
        slice any stage could take; the actual per-stage assignment is
        _hetero_assign's job."""
        if state.stage_slices:
            seen, out = set(), []
            for shape in state.stage_slices:
                for s in self._stage_matching(shape, pool):
                    if s.name not in seen:
                        seen.add(s.name)
                        out.append(s)
            return out
        per_slice_chips = -(-state.tpu_chips // max(state.num_slices, 1))
        if state.requested_slice:
            return self._stage_matching(state.requested_slice, pool)
        return [s for s in pool if s.type.chips >= per_slice_chips]

    def _hetero_assign(
        self,
        state: _GangState,
        candidates: List[SliceInfo],
        use_director: bool = True,
    ) -> Optional[List[SliceInfo]]:
        """Assign one DISTINCT candidate per stage shape, returned in
        STAGE order (slice_names[i] is stage i's slice — the pod
        slice-id label indexes it). Greedy: most demanding stage first,
        tightest fit per stage unless the director (gavel pricing)
        proposes a cheaper adequate slice. None = no full assignment —
        all-or-nothing, a partial match reserves NOTHING."""
        wants = [parse_slice_type(s) for s in state.stage_slices]
        order = sorted(range(len(wants)), key=lambda i: -wants[i].chips)
        taken: set = set()
        chosen: List[Optional[SliceInfo]] = [None] * len(wants)
        for i in order:
            cands = [
                s for s in self._stage_matching(state.stage_slices[i], candidates)
                if s.name not in taken
            ]
            if not cands:
                return None
            pick = None
            if use_director and self._director is not None:
                probe = _GangState(
                    tpu_chips=state.tpu_chips,
                    requested_slice=state.stage_slices[i],
                    num_slices=1, tenant=state.tenant)
                picked = self._director.choose_slices(probe, list(cands), 1)
                if picked and len(picked) == 1 and picked[0].name in {
                    s.name for s in cands
                }:
                    pick = picked[0]
            if pick is None:
                pick = min(cands, key=lambda s: s.type.chips)
            chosen[i] = pick
            taken.add(pick.name)
        return chosen  # complete by construction

    def _headroom(self, state: _GangState, usage=None, total_chips=0):
        """The gang's tenant-cap headroom per the director; None = no cap.
        Pass `usage`/`total_chips` when a reservation pass already holds
        them (avoids a redundant full-pool walk under the lock)."""
        if self._director is None:
            return None
        if usage is None:
            usage, total_chips = self._usage_by_tenant()
        return self._director.chips_headroom(state, usage, total_chips)

    def _grantable_slices(
        self, state: _GangState, pool, usage=None, total_chips=0
    ) -> List[SliceInfo]:
        """Matching slices a grant could ACTUALLY take: matching admits
        slices bigger than the request, so every availability probe
        (reserve, demand_view, shields, elastic what-ifs) must also drop
        slices whose chips alone exceed the tenant-cap headroom — or
        caps get breached at grant time / probes report capacity the
        grant step then refuses, wedging elastic decisions."""
        matching = self._matching_slices(state, pool)
        headroom = self._headroom(state, usage, total_chips)
        if headroom is None:
            return matching
        return [s for s in matching if s.type.chips <= headroom]

    def _try_reserve(
        self,
        key: str,
        state: _GangState,
        exclude: Optional[List[_GangState]] = None,
        usage: Optional[Dict[str, int]] = None,
        total_chips: int = 0,
    ) -> None:
        if state.slice_names or state.tpu_chips <= 0:
            return
        n = max(state.num_slices, 1)
        if usage is None and self._director is not None:
            usage, total_chips = self._usage_by_tenant()
        headroom = self._headroom(state, usage, total_chips)
        shielded = self._shielded_slices(exclude, usage, total_chips)
        candidates = [
            s for s in self._matching_slices(state, self._free_slices())
            if s.name not in shielded
            and (headroom is None or s.type.chips <= headroom)
        ]
        if len(candidates) < n:
            return  # all-or-nothing across ALL the gang's slices
        chosen = self._pick_slices(state, candidates, n, headroom)
        if chosen is None:
            return
        # write-AHEAD: the grant is durable before any bookkeeping moves
        self._journal_op(
            "grant", gang=key, slices=[s.name for s in chosen],
            state=self._gang_meta(state))
        for s in chosen:
            s.reserved_by = key
        state.slice_names = [s.name for s in chosen]
        state.granted_at = time.monotonic()
        self._note_change(key)
        self._record_admission(key, state)

    def _record_admission(self, key: str, state: _GangState) -> None:
        """Queue the just-ended wait as a gang.queue_wait span (runs
        under the admitter lock: no I/O here, only an append — the file
        write happens in _drain_spans outside the lock). cause=requeue
        marks a post-eviction re-grant — the goodput accountant books
        that wait as preemption downtime, a first admission as ordinary
        queue wait."""
        if self.tracer is None:
            return
        from kubedl_tpu.obs.trace import trace_id_for

        namespace, _, name = key.partition("/")
        waited = max(time.monotonic() - state.waiting_since, 0.0)
        self._span_queue.append(("gang.queue_wait", dict(
            duration_s=waited,
            trace_id=trace_id_for(namespace, name),
            job=name,
            namespace=namespace,
            cause="requeue" if state.preemptions > 0 else "initial",
            shape=state.requested_slice,
            slices=list(state.slice_names),
            preemptions=state.preemptions,
            tenant=state.tenant,
        )))

    def _drain_spans(self) -> None:
        """Export queued admission spans OUTSIDE the admitter lock —
        called from the public entry points whose passes can grant."""
        tracer = self.tracer
        if tracer is None:
            return
        with self._lock:
            if not self._span_queue:
                return
            pending, self._span_queue = self._span_queue, []
        for name, kwargs in pending:
            try:
                tracer.record(name, **kwargs)
            except Exception:  # noqa: BLE001 — tracing never blocks grants
                pass

    def _pick_slices(
        self,
        state: _GangState,
        candidates: List[SliceInfo],
        n: int,
        headroom: Optional[int],
    ) -> Optional[List[SliceInfo]]:
        """Choose the `n` slices a grant takes from the matching
        `candidates` — the ONE selection used by both the reservation
        pass and the elastic-grow directive so cap enforcement can't
        drift between them. Director pick (Gavel-style pricing) when it
        returns a valid subset, else tightest fits first (keep big
        slices free for big gangs); the cap binds on the SUM of the
        actual grant (multislice), retrying with the minimal-chips
        subset before giving up. None = no cap-fitting choice.

        Heterogeneous gangs (stage_slices) route through _hetero_assign:
        one distinct slice per stage shape, stage-ordered, all-or-
        nothing; when the gavel-priced pick breaches the tenant cap the
        tightest-per-stage assignment is retried before giving up."""
        if state.stage_slices:
            chosen = self._hetero_assign(state, candidates)
            if chosen is not None and headroom is not None and sum(
                s.type.chips for s in chosen
            ) > headroom:
                chosen = self._hetero_assign(
                    state, candidates, use_director=False)
            if chosen is not None and headroom is not None and sum(
                s.type.chips for s in chosen
            ) > headroom:
                return None
            return chosen
        chosen = None
        if self._director is not None:
            picked = self._director.choose_slices(state, list(candidates), n)
            if picked:
                by_name = {s.name for s in candidates}
                if len(picked) == n and all(s.name in by_name for s in picked):
                    chosen = picked
        tightest = sorted(candidates, key=lambda s: s.type.chips)[:n]
        if chosen is None:
            chosen = tightest
        if headroom is not None:
            if sum(s.type.chips for s in chosen) > headroom:
                chosen = tightest
            if sum(s.type.chips for s in chosen) > headroom:
                return None
        return chosen

    def _assign_solo(self, pod, chips: int) -> Optional[Placement]:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            existing = self._solo.get(key)
            if existing:
                return self._place_on_slice(pod, self._slices[existing])
            # gangs outrank solo pods: slices a feasible waiting gang
            # matches are off limits, or a trickle of standalone pods
            # would starve a multislice gang exactly like small gangs
            # would (see _reserve_waiting)
            shielded = self._shielded_slices(self._waiting_shields())
            candidates = [
                s for s in self._free_slices()
                if s.type.chips >= chips and s.name not in shielded
            ]
            if not candidates:
                return None
            best = min(candidates, key=lambda s: s.type.chips)
            best.reserved_by = key
            self._solo[key] = best.name
            self._note_avail()
            return self._place_on_slice(pod, best)

    def _place_on_slice(
        self, pod, info: SliceInfo, gang: Optional[_GangState] = None
    ) -> Placement:
        try:
            index = int(pod.metadata.labels.get(LABEL_REPLICA_INDEX, "0"))
        except ValueError:
            index = 0
        if gang is not None and gang.num_slices > 1:
            # worker id is PER SLICE (matches GKE's TPU_WORKER_ID scoping);
            # same contiguous-group convention as env injection
            _, index, _ = slice_group(gang.total_member, gang.num_slices, index)
        coords = host_coords(info.type)
        order = ring_order(coords)
        host = order[index % len(order)] if order else 0
        return Placement(
            node_name=f"{info.name}/host-{host}",
            slice_name=info.name,
            slice_type=info.type.name,
            topology=info.type.topology_str,
            worker_id=index,
            num_workers=max(info.type.num_hosts, 1),
        )

    def _mirror_podgroup(self, job, state: _GangState) -> None:
        """Keep an observable PodGroup object in the store (ref PodGroup CRD)."""
        pg = PodGroup(
            metadata=ObjectMeta(
                name=job.metadata.name, namespace=job.metadata.namespace
            ),
            spec=PodGroupSpec(
                min_member=state.min_member,
                tpu_chips=state.tpu_chips,
                tpu_slice=state.requested_slice,
                num_slices=state.num_slices,
            ),
            status=PodGroupStatus(
                phase="Reserved" if state.slice_names else "Pending",
                slice_name=state.slice_name or "",
                slice_names=list(state.slice_names),
            ),
        )
        try:
            existing = self.store.get(
                "PodGroup", pg.metadata.namespace, pg.metadata.name)
            if (
                existing.spec == pg.spec
                and (existing.status.phase, existing.status.slice_names)
                == (pg.status.phase, pg.status.slice_names)
            ):
                return  # common case: cached read says nothing to write
            # writing: re-read FRESH for a current resourceVersion
            existing = read_fresh(
                self.store, "PodGroup", pg.metadata.namespace, pg.metadata.name)
            pg.metadata = existing.metadata
            try:
                if existing.spec != pg.spec:
                    # spec changes (min_member, chips, slice request) ride
                    # the main path; status is preserved by the store
                    pg.metadata = self.store.update(pg).metadata
                if (existing.status.phase, existing.status.slice_names) != (
                    pg.status.phase, pg.status.slice_names
                ):
                    # phase/slice live in status -> /status subresource PUT
                    write_status(self.store, pg)
            except (Conflict, NotFound):
                pass  # concurrent writer/deletion: next pass re-mirrors
        except NotFound:
            try:
                # create strips status on subresource kinds; follow up with
                # a /status write when the desired status isn't the default
                created = self.store.create(pg)
                if pg.status != created.status:
                    pg.metadata = created.metadata
                    write_status(self.store, pg)
            except (AlreadyExists, Conflict, NotFound):
                pass
