"""TPU-slice gang admission — all-or-nothing placement onto pod slices.

Replaces the reference's kube-batch PodGroup implementation
(ref pkg/gang_schedule/batch_scheduler/scheduler.go:59-99) with slice-atomic
admission: a gang reserves one whole TPU slice or nothing. Two reference
gaps are fixed deliberately:
  * SchedulingPolicy.MinAvailable is honored (the reference always used total
    replicas — scheduler.go:66-69);
  * admission is atomic at the slice, so the "expectations vs async gang"
    race (SURVEY.md §7 hard parts) collapses to: pods stay Pending until the
    reservation exists, then all start together.

The admitter implements both the GangScheduler plugin contract (used by the
reconciler engine) and the executor's scheduler protocol (assign/release).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import (
    LABEL_REPLICA_INDEX,
    LABEL_SLICE_ID,
    ReplicaSpec,
    slice_group,
)
from kubedl_tpu.api.meta import ObjectMeta
from kubedl_tpu.core.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    read_fresh,
    write_status,
)
from kubedl_tpu.executor.tpu_topology import (
    Placement,
    SliceInfo,
    host_coords,
    parse_slice_type,
    ring_order,
)
from kubedl_tpu.gang.interface import ANNOTATION_GANG_NAME, GangScheduler


@dataclass
class PodGroupSpec:
    min_member: int = 0
    tpu_chips: int = 0
    tpu_slice: str = ""
    num_slices: int = 1


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Reserved
    slice_name: str = ""  # first reserved slice (printer column)
    slice_names: List[str] = field(default_factory=list)


@dataclass
class PodGroup:
    # podgroups CRD declares `subresources: status: {}` — phase/slice
    # writes must go through the store's update_status().
    STATUS_SUBRESOURCE = True

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)
    kind: str = "PodGroup"


@dataclass
class _GangState:
    min_member: int = 0
    tpu_chips: int = 0
    requested_slice: str = ""
    # reserved slices, ordered by slice-id; empty = waiting. A gang asks
    # for num_slices whole slices (multislice JAXJob spans several slices
    # over DCN) and gets all of them or none.
    slice_names: List[str] = field(default_factory=list)
    num_slices: int = 1
    total_member: int = 0  # total replicas (min_member can be lower)
    priority: int = 0
    seq: int = 0  # admission order for FIFO tie-break
    # owning job kind: gang keys are ns/name (reference parity — kube-batch
    # PodGroups are named after the job), so deletion paths must verify the
    # kind to avoid releasing a same-named other-kind job's gang
    kind: str = ""

    @property
    def slice_name(self) -> Optional[str]:
        return self.slice_names[0] if self.slice_names else None


class TPUSliceAdmitter(GangScheduler):
    """Pool of TPU slices + an unlimited local CPU 'node'."""

    name = "tpu-slice"

    def __init__(self, store: ObjectStore, slices: Optional[List[SliceInfo]] = None) -> None:
        self.store = store
        self._lock = threading.RLock()
        self._slices: Dict[str, SliceInfo] = {s.name: s for s in (slices or [])}
        self._gangs: Dict[str, _GangState] = {}
        # implicit single-pod reservations: pod key -> slice name
        self._solo: Dict[str, str] = {}
        self._seq = 0  # monotonic gang admission counter

    @classmethod
    def with_pool(cls, store: ObjectStore, slice_types: List[str]) -> "TPUSliceAdmitter":
        infos = []
        for i, name in enumerate(slice_types):
            st = parse_slice_type(name)
            infos.append(SliceInfo(name=f"slice-{i}-{st.name}", type=st))
        return cls(store, infos)

    def set_pool(self, infos: List[SliceInfo]) -> None:
        """Replace the slice pool (node-inventory updates, k8s/nodes.py).
        Reservations carry over by slice name; gangs whose slice vanished
        OR changed shape go back to waiting and re-reserve. Affected
        PodGroup mirrors are re-written so dashboards never show a
        reservation on hardware that no longer exists."""
        with self._lock:
            old = self._slices
            new: Dict[str, SliceInfo] = {}
            # slice names whose reservation did NOT carry over (gone, or
            # the node pool was re-provisioned with a different shape)
            invalidated = set(old)
            for info in infos:
                prev = old.get(info.name)
                if prev is not None and prev.type == info.type:
                    info.reserved_by = prev.reserved_by
                    invalidated.discard(info.name)
                new[info.name] = info
            self._slices = new
            changed_keys = []
            for key, state in self._gangs.items():
                if state.slice_names and any(
                    s not in new or s in invalidated for s in state.slice_names
                ):
                    # all-or-nothing holds for revocation too: losing any
                    # slice of a multislice gang frees the survivors and
                    # sends the whole gang back to waiting
                    for s in state.slice_names:
                        info = new.get(s)
                        if info is not None and info.reserved_by == key:
                            info.reserved_by = None
                    state.slice_names = []
                    changed_keys.append(key)
            self._solo = {
                pod_key: sname for pod_key, sname in self._solo.items()
                if sname in new and sname not in invalidated
            }
            changed_keys.extend(self._reserve_waiting())
        for key in changed_keys:
            self._remirror_podgroup_status(key)

    def _remirror_podgroup_status(self, gang_key: str) -> None:
        """Refresh the PodGroup mirror's status after a pool-driven
        reservation change (no job reconcile fires for those)."""
        namespace, _, name = gang_key.partition("/")
        with self._lock:
            state = self._gangs.get(gang_key)
            if state is None:
                return
            phase = "Reserved" if state.slice_names else "Pending"
            slice_name = state.slice_name or ""
            slice_names = list(state.slice_names)
        try:
            # the no-change check may serve from the informer cache; a
            # WRITE needs the fresh resourceVersion (a cached rv makes
            # the swallowed Conflict below permanent — pool changes get
            # no follow-up reconcile to retry)
            pg = self.store.get("PodGroup", namespace, name)
            if (pg.status.phase, pg.status.slice_names) == (phase, slice_names):
                return
            pg = read_fresh(self.store, "PodGroup", namespace, name)
        except NotFound:
            return
        if (pg.status.phase, pg.status.slice_names) == (phase, slice_names):
            return
        pg.status.phase = phase
        pg.status.slice_name = slice_name
        pg.status.slice_names = slice_names
        try:
            write_status(self.store, pg)
        except (Conflict, NotFound):
            pass  # next mirror pass converges

    # ------------------------------------------------------------------
    # GangScheduler contract
    # ------------------------------------------------------------------

    def create_gang(self, job, replicas: Dict[str, ReplicaSpec]):
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            state = self._gangs.get(key)
            if state is None:
                total = sum(int(s.replicas or 0) for s in replicas.values())
                sched = (job.spec.run_policy.scheduling_policy
                         if getattr(job.spec, "run_policy", None) else None)
                min_member = total
                requested_slice = ""
                priority = 0
                if sched is not None:
                    # Honor MinAvailable (the reference ignored it).
                    if sched.min_available:
                        min_member = min(sched.min_available, total)
                    requested_slice = sched.tpu_slice
                    priority = int(sched.priority or 0)
                chips = sum(
                    int(s.replicas or 0) * s.template.spec.tpu_chips()
                    for s in replicas.values()
                )
                num_slices = max(int(getattr(job.spec, "num_slices", 1) or 1), 1)
                self._seq += 1
                state = _GangState(
                    min_member=min_member, tpu_chips=chips,
                    requested_slice=requested_slice,
                    num_slices=num_slices, total_member=total,
                    priority=priority, seq=self._seq,
                    kind=getattr(job, "kind", "") or "",
                )
                self._gangs[key] = state
            self._reserve_waiting()
        self._mirror_podgroup(job, state)
        return state

    def bind_pod_to_gang(self, job, pod) -> None:
        pod.metadata.annotations[ANNOTATION_GANG_NAME] = (
            f"{job.metadata.namespace}/{job.metadata.name}"
        )
        pod.spec.scheduler_name = self.name

    def get_gang(self, namespace: str, name: str):
        with self._lock:
            return self._gangs.get(f"{namespace}/{name}")

    def delete_gang(self, job, expected_kind: str = "") -> None:
        """Release the job's gang. `expected_kind` (when set) makes the
        pop conditional UNDER THE LOCK: gang keys are ns/name (reference
        parity — kube-batch PodGroups are named after the job), so a
        deletion path racing a same-named job of another kind must not
        release the live record a check-then-act outside the lock could."""
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            state = self._gangs.get(key)
            if state is not None and expected_kind and state.kind not in (
                "", expected_kind
            ):
                return  # another kind's live gang took the key — not ours
            self._gangs.pop(key, None)
            if state:
                for sname in state.slice_names:
                    info = self._slices.get(sname)
                    if info and info.reserved_by == key:
                        info.reserved_by = None
        try:
            self.store.delete("PodGroup", job.metadata.namespace, job.metadata.name)
        except NotFound:
            pass

    # ------------------------------------------------------------------
    # Executor scheduler protocol
    # ------------------------------------------------------------------

    def assign(self, pod) -> Optional[Placement]:
        chips = pod.spec.tpu_chips()
        gang_key = pod.metadata.annotations.get(ANNOTATION_GANG_NAME)
        if gang_key is None:
            if chips <= 0:
                return Placement(node_name="local-cpu")
            return self._assign_solo(pod, chips)
        with self._lock:
            state = self._gangs.get(gang_key)
            if state is None:
                return None  # gang not created yet; stay Pending
            if state.tpu_chips <= 0:
                return Placement(node_name="local-cpu")
            if not state.slice_names:
                self._reserve_waiting()
            if not state.slice_names:
                return None  # no slices free (or higher-priority gangs ahead)
            # multislice: the pod's slice-id label picks which reserved
            # slice it lands on (workloads/jaxjob.py stamps contiguous
            # worker groups); single-slice gangs have exactly one entry
            try:
                slice_idx = int(pod.metadata.labels.get(LABEL_SLICE_ID, "0"))
            except ValueError:
                slice_idx = 0
            if not (0 <= slice_idx < len(state.slice_names)):
                return None  # label out of range for the reservation
            info = self._slices[state.slice_names[slice_idx]]
            return self._place_on_slice(pod, info, gang=state)

    def release(self, pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            slice_name = self._solo.pop(key, None)
            if slice_name:
                info = self._slices.get(slice_name)
                if info and info.reserved_by == key:
                    info.reserved_by = None
        # Gang reservations outlive individual pods (restarts keep the
        # slice); they free on delete_gang.

    def utilization(self) -> Dict:
        """Pool occupancy snapshot (BASELINE.md "slice utilization" gauge)."""
        with self._lock:
            slices = list(self._slices.values())
            total_chips = sum(s.type.chips for s in slices)
            reserved = [s for s in slices if s.reserved_by is not None]
            reserved_chips = sum(s.type.chips for s in reserved)
            return {
                "slices_total": len(slices),
                "slices_reserved": len(reserved),
                "chips_total": total_chips,
                "chips_reserved": reserved_chips,
                "utilization": (reserved_chips / total_chips) if total_chips else 0.0,
                "slices": [
                    {
                        "name": s.name,
                        "type": s.type.name,
                        "chips": s.type.chips,
                        "reserved_by": s.reserved_by or "",
                    }
                    for s in slices
                ],
            }

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _free_slices(self) -> List[SliceInfo]:
        return [s for s in self._slices.values() if s.reserved_by is None]

    def _reserve_waiting(self) -> List[str]:
        """Grant free slices to waiting gangs in (priority desc, FIFO) order
        so a freed slice goes to the front of the queue, not to whichever
        gang's executor poll happens to run next. Returns the keys of
        gangs that obtained a reservation in this pass."""
        waiting = sorted(
            (
                (k, s) for k, s in self._gangs.items()
                if not s.slice_names and s.tpu_chips > 0
            ),
            key=lambda kv: (-kv[1].priority, kv[1].seq),
        )
        granted = []
        shielded: List[_GangState] = []
        for key, state in waiting:
            self._try_reserve(key, state, shielded)
            if state.slice_names:
                granted.append(key)
            elif self._feasible(state):
                # Anti-starvation shield: a feasible-but-unsatisfied gang
                # (e.g. a multislice gang holding out for N simultaneously
                # free slices) keeps first claim on every slice matching
                # its demand — later gangs may only reserve slices OUTSIDE
                # that set, or a steady stream of small jobs would snatch
                # each freed slice forever (the gang never holds partial
                # reservations). Gangs with disjoint demands (different
                # slice type) still proceed; infeasible gangs (demand
                # exceeds the pool itself) shield nothing.
                shielded.append(state)
        return granted

    def _feasible(self, state: _GangState) -> bool:
        """Could this gang EVER be satisfied by the current pool (counting
        busy slices as eventually freeable)? Gates the anti-starvation
        shield so an impossible request doesn't wedge the queue."""
        return len(self._matching_slices(state, self._slices.values())) >= max(
            state.num_slices, 1
        )

    def _shielded_slices(self, exclude: Optional[List[_GangState]] = None):
        """Names of free slices held back for earlier waiting gangs."""
        if not exclude:
            return set()
        out = set()
        for g in exclude:
            out.update(s.name for s in self._matching_slices(g, self._free_slices()))
        return out

    def _waiting_shields(self) -> List[_GangState]:
        """Feasible waiting gangs, as seen by the SOLO-pod path: standalone
        pods must not snatch slices a queued gang is holding out for."""
        return [
            s for s in self._gangs.values()
            if not s.slice_names and s.tpu_chips > 0 and self._feasible(s)
        ]

    def _matching_slices(self, state: _GangState, pool) -> List[SliceInfo]:
        """Slices that satisfy the gang's PER-SLICE demand (explicit slice
        type, or chips: the job's total divides over its slices; ceil keeps
        ragged specs safe)."""
        per_slice_chips = -(-state.tpu_chips // max(state.num_slices, 1))
        if state.requested_slice:
            want = parse_slice_type(state.requested_slice)
            return [
                s for s in pool
                if s.type.generation == want.generation and s.type.chips >= want.chips
            ]
        return [s for s in pool if s.type.chips >= per_slice_chips]

    def _try_reserve(
        self,
        key: str,
        state: _GangState,
        exclude: Optional[List[_GangState]] = None,
    ) -> None:
        if state.slice_names or state.tpu_chips <= 0:
            return
        n = max(state.num_slices, 1)
        shielded = self._shielded_slices(exclude)
        candidates = [
            s for s in self._matching_slices(state, self._free_slices())
            if s.name not in shielded
        ]
        if len(candidates) < n:
            return  # all-or-nothing across ALL the gang's slices
        # tightest fits first — keep big slices free for big gangs
        chosen = sorted(candidates, key=lambda s: s.type.chips)[:n]
        for s in chosen:
            s.reserved_by = key
        state.slice_names = [s.name for s in chosen]

    def _assign_solo(self, pod, chips: int) -> Optional[Placement]:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            existing = self._solo.get(key)
            if existing:
                return self._place_on_slice(pod, self._slices[existing])
            # gangs outrank solo pods: slices a feasible waiting gang
            # matches are off limits, or a trickle of standalone pods
            # would starve a multislice gang exactly like small gangs
            # would (see _reserve_waiting)
            shielded = self._shielded_slices(self._waiting_shields())
            candidates = [
                s for s in self._free_slices()
                if s.type.chips >= chips and s.name not in shielded
            ]
            if not candidates:
                return None
            best = min(candidates, key=lambda s: s.type.chips)
            best.reserved_by = key
            self._solo[key] = best.name
            return self._place_on_slice(pod, best)

    def _place_on_slice(
        self, pod, info: SliceInfo, gang: Optional[_GangState] = None
    ) -> Placement:
        try:
            index = int(pod.metadata.labels.get(LABEL_REPLICA_INDEX, "0"))
        except ValueError:
            index = 0
        if gang is not None and gang.num_slices > 1:
            # worker id is PER SLICE (matches GKE's TPU_WORKER_ID scoping);
            # same contiguous-group convention as env injection
            _, index, _ = slice_group(gang.total_member, gang.num_slices, index)
        coords = host_coords(info.type)
        order = ring_order(coords)
        host = order[index % len(order)] if order else 0
        return Placement(
            node_name=f"{info.name}/host-{host}",
            slice_name=info.name,
            slice_type=info.type.name,
            topology=info.type.topology_str,
            worker_id=index,
            num_workers=max(info.type.num_hosts, 1),
        )

    def _mirror_podgroup(self, job, state: _GangState) -> None:
        """Keep an observable PodGroup object in the store (ref PodGroup CRD)."""
        pg = PodGroup(
            metadata=ObjectMeta(
                name=job.metadata.name, namespace=job.metadata.namespace
            ),
            spec=PodGroupSpec(
                min_member=state.min_member,
                tpu_chips=state.tpu_chips,
                tpu_slice=state.requested_slice,
                num_slices=state.num_slices,
            ),
            status=PodGroupStatus(
                phase="Reserved" if state.slice_names else "Pending",
                slice_name=state.slice_name or "",
                slice_names=list(state.slice_names),
            ),
        )
        try:
            existing = self.store.get(
                "PodGroup", pg.metadata.namespace, pg.metadata.name)
            if (
                existing.spec == pg.spec
                and (existing.status.phase, existing.status.slice_names)
                == (pg.status.phase, pg.status.slice_names)
            ):
                return  # common case: cached read says nothing to write
            # writing: re-read FRESH for a current resourceVersion
            existing = read_fresh(
                self.store, "PodGroup", pg.metadata.namespace, pg.metadata.name)
            pg.metadata = existing.metadata
            try:
                if existing.spec != pg.spec:
                    # spec changes (min_member, chips, slice request) ride
                    # the main path; status is preserved by the store
                    pg.metadata = self.store.update(pg).metadata
                if (existing.status.phase, existing.status.slice_names) != (
                    pg.status.phase, pg.status.slice_names
                ):
                    # phase/slice live in status -> /status subresource PUT
                    write_status(self.store, pg)
            except (Conflict, NotFound):
                pass  # concurrent writer/deletion: next pass re-mirrors
        except NotFound:
            try:
                # create strips status on subresource kinds; follow up with
                # a /status write when the desired status isn't the default
                created = self.store.create(pg)
                if pg.status != created.status:
                    pg.metadata = created.metadata
                    write_status(self.store, pg)
            except (AlreadyExists, Conflict, NotFound):
                pass
