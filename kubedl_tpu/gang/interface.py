"""GangScheduler plugin interface (ref pkg/gang_schedule/interface.go:30-50).

Same contract as the reference — create/bind/get/delete — with the kube-batch
PodGroup implementation replaced by all-or-nothing TPU-slice admission
(SURVEY.md §2.4): a gang maps to one pod slice; partial placement is refused.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import ReplicaSpec

ANNOTATION_GANG_NAME = "kubedl.io/gang-name"


def gang_pods(store, gang_key: str, kind: str = "") -> List:
    """The live pods of one gang — the ONE pod-selection used by every
    path that messages or deletes a gang's pods (capacity scheduler,
    operator slice-failure handling). Gang keys are ns/name, so a
    same-named job of ANOTHER kind carries the identical annotation: the
    controller-ref kind guard keeps other jobs' pods untouched. Returns
    [] when the listing fails (callers treat that as "cannot act")."""
    namespace = gang_key.partition("/")[0]
    try:
        pods = store.list("Pod", namespace=namespace)
    except Exception:  # noqa: BLE001 — store racing shutdown
        return []
    out = []
    for pod in pods:
        if pod.metadata.annotations.get(ANNOTATION_GANG_NAME) != gang_key:
            continue
        ref = pod.metadata.controller_ref()
        if kind and (ref is None or ref.kind != kind):
            continue
        out.append(pod)
    return out


@dataclass
class GangSnapshot:
    """Read-only copy of one gang's scheduling state, safe to inspect
    outside the admitter's lock (sched/capacity.py works on these)."""

    key: str = ""  # "namespace/name"
    kind: str = ""
    tenant: str = ""
    priority: int = 0
    seq: int = 0
    tpu_chips: int = 0
    num_slices: int = 1
    requested_slice: str = ""
    admissible_slices: List[str] = field(default_factory=list)
    # heterogeneous MPMD pipeline gang (JAXJob spec.pipeline.stageSlices):
    # slice i of the reservation must match stage_slices[i]; admission
    # stays all-or-nothing across the whole per-stage assignment
    stage_slices: List[str] = field(default_factory=list)
    # mixed-ROLE gang (JAXJob spec.rl): roles[i] names what slice i runs
    # ("actor" | "learner"); the shapes ride stage_slices, so the actor
    # gang and learner gang admit as ONE all-or-nothing unit — an actor
    # fleet without a learner (or vice versa) reserves nothing
    roles: List[str] = field(default_factory=list)
    slice_names: List[str] = field(default_factory=list)
    reserved_chips: int = 0
    hold_until: float = 0.0  # monotonic; 0 = not held
    preemptions: int = 0
    waiting_since: float = 0.0  # monotonic; when the gang last lost/lacked slices
    granted_at: float = 0.0  # monotonic; when the current reservation was made
    # live-reshard opt-in (JAXJob spec.elastic.liveReshard): resizes may be
    # executed as an in-place RESIZE control message to the running pods
    # instead of checkpoint-then-evict (sched/capacity.py)
    live_reshard: bool = False
    # the job's declared quiesce budget (spec.elastic.quiesceTimeoutS;
    # 0 = use the scheduler default) — the reply deadline must cover it
    quiesce_s: float = 0.0

    @property
    def namespace(self) -> str:
        return self.key.partition("/")[0]

    @property
    def name(self) -> str:
        return self.key.partition("/")[2]


class CapacityDirector(abc.ABC):
    """Policy hooks a capacity scheduler plugs into the gang admitter.

    The admitter stays the mechanism (atomic reservation, shields,
    mirroring); a director owns the waiting-gang policy. Every hook is
    invoked UNDER the admitter's lock — implementations must not call
    back into the admitter and may only take leaf locks (tenant quota
    counters). `usage` maps tenant -> chips currently reserved; the
    caller keeps it current across grants within one pass.
    """

    @abc.abstractmethod
    def order_waiting(self, waiting: List, usage: Dict[str, int], total_chips: int) -> List:
        """Order the waiting gang states for this reservation pass."""

    @abc.abstractmethod
    def may_reserve(self, gang, usage: Dict[str, int], total_chips: int) -> bool:
        """Gate a reservation (tenant caps). A rejected gang is skipped
        WITHOUT shielding slices (it is not starved, it is capped)."""

    @abc.abstractmethod
    def choose_slices(self, gang, candidates: List, n: int) -> Optional[List]:
        """Pick `n` of the matching free `candidates` (heterogeneity
        pricing); None falls back to the admitter's tightest-fit."""

    def chips_headroom(self, gang, usage: Dict[str, int], total_chips: int) -> Optional[int]:
        """Hard ceiling on the chips an actual grant for this gang may
        take (tenant cap minus current usage); None = unlimited. The
        admitter checks the CHOSEN slices against this — matching admits
        slices bigger than the request, so a demand-based gate alone
        would let an oversized grant breach the cap."""
        return None


class GangScheduler(abc.ABC):
    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def create_gang(self, job, replicas: Dict[str, ReplicaSpec]):
        """Idempotently create the gang entity for a job."""

    @abc.abstractmethod
    def bind_pod_to_gang(self, job, pod) -> None:
        """Mark a pod as a member of its job's gang."""

    @abc.abstractmethod
    def get_gang(self, namespace: str, name: str): ...

    @abc.abstractmethod
    def delete_gang(self, job, expected_kind: str = "") -> None:
        """Release the job's gang. When `expected_kind` is set, the
        implementation must skip the release if the recorded gang belongs
        to a different job kind (gang keys are ns/name, so deletion paths
        can race a same-named job of another kind)."""


class GangRegistry:
    """Ref pkg/gang_schedule/registry/registry.go:27-70."""

    def __init__(self) -> None:
        self._schedulers: Dict[str, GangScheduler] = {}

    def register(self, scheduler: GangScheduler) -> None:
        self._schedulers[scheduler.name] = scheduler

    def get(self, name: str) -> Optional[GangScheduler]:
        return self._schedulers.get(name)

    def names(self):
        return sorted(self._schedulers)
