"""GangScheduler plugin interface (ref pkg/gang_schedule/interface.go:30-50).

Same contract as the reference — create/bind/get/delete — with the kube-batch
PodGroup implementation replaced by all-or-nothing TPU-slice admission
(SURVEY.md §2.4): a gang maps to one pod slice; partial placement is refused.
"""
from __future__ import annotations

import abc
from typing import Dict, Optional

from kubedl_tpu.api.common import ReplicaSpec

ANNOTATION_GANG_NAME = "kubedl.io/gang-name"


class GangScheduler(abc.ABC):
    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def create_gang(self, job, replicas: Dict[str, ReplicaSpec]):
        """Idempotently create the gang entity for a job."""

    @abc.abstractmethod
    def bind_pod_to_gang(self, job, pod) -> None:
        """Mark a pod as a member of its job's gang."""

    @abc.abstractmethod
    def get_gang(self, namespace: str, name: str): ...

    @abc.abstractmethod
    def delete_gang(self, job, expected_kind: str = "") -> None:
        """Release the job's gang. When `expected_kind` is set, the
        implementation must skip the release if the recorded gang belongs
        to a different job kind (gang keys are ns/name, so deletion paths
        can race a same-named job of another kind)."""


class GangRegistry:
    """Ref pkg/gang_schedule/registry/registry.go:27-70."""

    def __init__(self) -> None:
        self._schedulers: Dict[str, GangScheduler] = {}

    def register(self, scheduler: GangScheduler) -> None:
        self._schedulers[scheduler.name] = scheduler

    def get(self, name: str) -> Optional[GangScheduler]:
        return self._schedulers.get(name)

    def names(self):
        return sorted(self._schedulers)
