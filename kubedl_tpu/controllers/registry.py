"""Workload controller registry (ref controllers/controllers.go:31-47 +
per-workload add_*.go init() registration), gated per deploy by the
workload-gate expression."""
from __future__ import annotations

from typing import Callable, List

from kubedl_tpu.utils.workload_gate import is_workload_enabled

# name -> controller factory; populated below as workloads are implemented.
_FACTORIES: dict = {}


def register_workload(name: str, factory: Callable) -> None:
    _FACTORIES[name] = factory


def enabled_controllers(expr: str = "*") -> List:
    out = []
    for name in sorted(_FACTORIES):
        if is_workload_enabled(name, expr):
            out.append(_FACTORIES[name]())
    return out


def _populate() -> None:
    # Imported lazily so api/controller modules stay import-cycle free.
    try:
        from kubedl_tpu.workloads import tensorflow, pytorch, xgboost, xdl, jaxjob  # noqa: F401
    except ImportError:
        pass


_populate()
