"""The shared job reconciler engine — one engine drives every workload.

Re-derives the reference's generic runtime (ref pkg/job_controller/job.go:56-345,
pod.go:212-442, service.go:188-295, expectations.go) as a single
watch-driven reconcile engine over the native object store:

  watch events -> expectation bookkeeping -> workqueue -> reconcile(key):
    gang create -> code-sync inject -> list+claim pods/services ->
    backoff/deadline checks -> terminal cleanup (CleanPodPolicy, TTL, gang
    delete) OR per-replica-type pod/service diffing -> workload status
    machine -> status write-back (optimistic, conflict-aware).

Deliberate fixes over the reference, called out inline:
  * services-per-replica is asked of the workload via
    `needs_service_for_replica` instead of special-casing PyTorch
    (ref job.go:223-227);
  * expectations use increment semantics instead of set semantics so two
    creates in one pass cannot cancel each other's bookkeeping.
"""
from __future__ import annotations

import copy
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import (
    CleanPodPolicy,
    JobConditionType,
    JobStatus,
    LABEL_JOB_NAME,
    LABEL_JOB_ROLE,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    JOB_ROLE_MASTER,
    REASON_JOB_CREATED,
    REASON_JOB_FAILED,
    ReplicaSpec,
    RestartPolicy,
    initialize_replica_statuses,
    is_created,
    is_failed,
    is_restarting,
    is_running,
    is_succeeded,
    update_job_conditions,
    update_job_replica_statuses,
)
from kubedl_tpu.api.meta import OwnerReference, now
from kubedl_tpu.api.pod import (
    ContainerPort,
    Pod,
    PodPhase,
    PodRestartPolicy,
    Service,
    ServiceSpec,
)
from kubedl_tpu.controllers import utils
from kubedl_tpu.controllers.interface import WorkloadController
from kubedl_tpu.core import events as ev
from kubedl_tpu.core.expectations import ControllerExpectations
from kubedl_tpu.core.manager import ControllerRunner, Result
from kubedl_tpu.core.store import (
    ADDED,
    DELETED,
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    read_fresh,
    write_status,
)
from kubedl_tpu.utils.exit_codes import is_retryable_exit_code
from kubedl_tpu.utils.joblog import job_logger

log = logging.getLogger("kubedl_tpu.engine")

EXIT_CODE_MAGIC = 0xBEEF  # "no terminated default container seen" sentinel

# Failure-retry pacing (ref BackoffStatesQueue rate limiter defaults).
BACKOFF_BASE_DELAY_S = 0.005
BACKOFF_MAX_DELAY_S = 60.0


@dataclass
class EngineConfig:
    enable_gang_scheduling: bool = False
    cluster_domain: str = ""  # CUSTOM_CLUSTER_DOMAIN equivalent
    # Pod-template mutation hooks applied after set_cluster_spec, e.g. the
    # GKE TPU adapter (k8s/gke.py): fn(job, template, rt, index, spec)
    pod_mutators: List = field(default_factory=list)


def pods_expectation_key(job_key: str, rt: str) -> str:
    return f"{job_key}/{rt.lower()}/pods"


def services_expectation_key(job_key: str, rt: str) -> str:
    return f"{job_key}/{rt.lower()}/services"


class JobReconciler:
    """One instance per workload kind, sharing a store/recorder/metrics."""

    def __init__(
        self,
        store: ObjectStore,
        controller: WorkloadController,
        recorder=None,
        metrics=None,
        gang_scheduler=None,
        code_syncer=None,
        config: Optional[EngineConfig] = None,
    ) -> None:
        self.store = store
        self.controller = controller
        self.recorder = recorder or ev.EventRecorder(store)
        self.metrics = metrics
        self.gang = gang_scheduler
        self.code_syncer = code_syncer
        self.config = config or EngineConfig()
        self.expectations = ControllerExpectations()
        self.runner: Optional[ControllerRunner] = None
        # flight recorder (obs/trace.py Tracer), wired by the operator:
        # each reconcile becomes a span on the job's timeline, keyed by
        # the same gang-level trace id the executor injects into pods
        self.tracer = None
        # Dedicated failure-backoff states (ref job_controller.go:85-88
        # BackoffStatesQueue) — counts only observed pod failures, never
        # status-write conflicts, so conflict churn can't burn the
        # backoff limit.
        self._failure_backoff: Dict[str, int] = {}

    # ------------------------------------------------------------------
    # Watch wiring (ref tfjob_controller.go:128-164 and pod.go:53-163)
    # ------------------------------------------------------------------

    def setup(self, runner: ControllerRunner) -> None:
        self.runner = runner
        runner.watch(self.controller.kind, self._on_job_event)
        runner.watch("Pod", self._on_pod_event)
        runner.watch("Service", self._on_service_event)

    def _on_job_event(self, event) -> None:
        job = event.obj
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        if event.type == DELETED:
            self._failure_backoff.pop(key, None)
            for rt in self.controller.replica_specs(job):
                self.expectations.delete_expectations(pods_expectation_key(key, rt))
                self.expectations.delete_expectations(services_expectation_key(key, rt))
            # Free the gang reservation for a job deleted MID-RUN: the
            # terminal path's delete_gang never runs for a deletion, and
            # per-pod release deliberately keeps the slice (restarts).
            # Without this, deleting a Running job pinned its slice forever
            # (VERDICT r3 weak #5); the pods themselves are reaped by the
            # store's ownerRef GC.
            if self.config.enable_gang_scheduling and self.gang is not None:
                self._delete_gang_if_ours(job.metadata.namespace,
                                          job.metadata.name)
                # an in-flight reconcile may re-create the gang AFTER this
                # ran; re-enqueue so reconcile's NotFound branch converges
                # even for a waiting gang with zero pods (no pod-DELETED
                # events will ever fire for it)
                self.runner.enqueue(key)
            if self.metrics:
                self.metrics.deleted_inc()
                self.metrics.observe_gone(key)
            return
        if event.type == ADDED and self.metrics and not job.status.conditions:
            self.metrics.created_inc()
        self.runner.enqueue(key)

    def _resolve_owner_key(self, obj) -> Optional[str]:
        ref = obj.metadata.controller_ref()
        if ref is None or ref.kind != self.controller.kind:
            return None
        return f"{obj.metadata.namespace}/{ref.name}"

    def _on_pod_event(self, event) -> None:
        pod = event.obj
        key = self._resolve_owner_key(pod)
        if key is None:
            return
        rt = pod.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        if event.type == ADDED:
            self.expectations.creation_observed(pods_expectation_key(key, rt))
        elif event.type == DELETED:
            self.expectations.deletion_observed(pods_expectation_key(key, rt))
        self.runner.enqueue(key)

    def _on_service_event(self, event) -> None:
        svc = event.obj
        key = self._resolve_owner_key(svc)
        if key is None:
            return
        rt = svc.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        if event.type == ADDED:
            self.expectations.creation_observed(services_expectation_key(key, rt))
        elif event.type == DELETED:
            self.expectations.deletion_observed(services_expectation_key(key, rt))
        self.runner.enqueue(key)

    # ------------------------------------------------------------------
    # Reconcile entry (ref tfjob_controller.go:90-124 -> job.go:56-266)
    # ------------------------------------------------------------------

    def reconcile(self, key: str) -> Result:
        if self.tracer is None:
            return self._reconcile(key)
        namespace, name = key.split("/", 1)
        from kubedl_tpu.obs.trace import trace_id_for

        with self.tracer.span(
            "operator.reconcile",
            trace_id=trace_id_for(namespace, name),
            job=name, namespace=namespace, kind=self.controller.kind,
        ):
            return self._reconcile(key)

    def _reconcile(self, key: str) -> Result:
        namespace, name = key.split("/", 1)
        try:
            job = self.store.get(self.controller.kind, namespace, name)
        except NotFound:
            # Level-triggered gang cleanup: the edge-triggered delete_gang
            # in _on_job_event can lose to an in-flight reconcile that
            # re-creates the gang AFTER it ran (read job -> job deleted ->
            # delete_gang -> create_gang). The DELETED handler re-enqueues
            # this key and pod-DELETED events from the store's GC re-enqueue
            # it again, so clearing the reservation here makes slice
            # release converge regardless of interleaving.
            if self.config.enable_gang_scheduling and self.gang is not None:
                self._delete_gang_if_ours(namespace, name)
            return Result()

        self.controller.set_defaults(job)
        replicas = self.controller.replica_specs(job)

        if not self._satisfied_expectations(key, replicas):
            return Result()

        try:
            return self._reconcile_job(job, replicas)
        except Conflict:
            return Result(requeue=True)

    def _delete_gang_if_ours(self, namespace: str, name: str) -> None:
        """Release the gang for a deleted job — but only if the recorded
        gang actually belongs to this engine's kind (the admitter checks
        under its own lock; schedulers without kind-aware deletion fall
        back to an unconditional release)."""
        if self.gang.get_gang(namespace, name) is None:
            return
        ghost = self.controller.job_type()()
        ghost.metadata.namespace, ghost.metadata.name = namespace, name
        self.gang.delete_gang(ghost, expected_kind=self.controller.kind)

    def _satisfied_expectations(self, key: str, replicas) -> bool:
        return all(
            self.expectations.satisfied(pods_expectation_key(key, rt))
            and self.expectations.satisfied(services_expectation_key(key, rt))
            for rt in replicas
        )

    # ------------------------------------------------------------------
    # The master sync (ref job.go:56-266)
    # ------------------------------------------------------------------

    def _reconcile_job(self, job, replicas: Dict[str, ReplicaSpec]) -> Result:
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        status: JobStatus = copy.deepcopy(self.controller.job_status(job))
        old_status = copy.deepcopy(status)
        run_policy = self.controller.run_policy(job)

        if not status.conditions:
            update_job_conditions(
                status,
                JobConditionType.CREATED,
                REASON_JOB_CREATED,
                f"{self.controller.kind} {job.metadata.name} is created.",
            )

        if self.config.enable_gang_scheduling and self.gang is not None:
            self.gang.create_gang(job, replicas)

        if self.code_syncer is not None:
            # a bad annotation must not wedge the reconcile loop
            # (ref job.go:99-103 logs and continues on code-sync errors)
            try:
                self.code_syncer.inject(job, replicas)
            except Exception as e:
                self.recorder.warning(job, "FailedCodeSync", f"code-sync injection failed: {e}")

        pods = self.get_pods_for_job(job)
        services = self.get_services_for_job(job)

        previous_retry = self._failure_backoff.get(key, 0)
        active_pods = utils.filter_active_pods(pods)
        active = len(active_pods)
        failed = utils.filter_pod_count(pods, PodPhase.FAILED)
        total_replicas = utils.get_total_replicas(replicas)
        prev_failed = utils.get_total_failed_replicas(status.replica_statuses)

        job_exceeds_limit = False
        failure_message = ""
        job_has_new_failure = failed > prev_failed
        if run_policy.backoff_limit is not None:
            exceeds_backoff = (
                job_has_new_failure
                and active != total_replicas
                and previous_retry + 1 > run_policy.backoff_limit
            )
            past_backoff = self._past_backoff_limit(run_policy, replicas, pods)
            if exceeds_backoff or past_backoff:
                job_exceeds_limit = True
                failure_message = (
                    f"Job {job.metadata.name} has failed because it has reached "
                    f"the specified backoff limit"
                )
        if not job_exceeds_limit and self._past_active_deadline(run_policy, status):
            job_exceeds_limit = True
            failure_message = (
                f"Job {job.metadata.name} has failed because it was active "
                f"longer than specified deadline"
            )
            status.completion_time = status.completion_time or now()

        if is_succeeded(status) or is_failed(status) or job_exceeds_limit:
            return self._finalize_job(
                job, replicas, status, old_status, run_policy, pods,
                job_exceeds_limit, failure_message,
            )

        if self.controller.restart_whole_gang(job, replicas):
            failed_retryable = self._gang_failed_retryable(replicas, pods)
            if failed_retryable:
                return self._restart_gang(
                    job, replicas, status, old_status, pods, failed_retryable,
                    previous_retry, job_has_new_failure,
                )

        restart = [False]
        for rtype in self.controller.reconcile_orders():
            rt_key = str(rtype.value)
            spec = replicas.get(rt_key)
            if spec is None:
                continue
            self._reconcile_pods(job, status, pods, rt_key, spec, replicas, restart)
            # Generalized from the reference's PyTorch-only special case
            # (ref job.go:223-227).
            if self.controller.needs_service_for_replica(rt_key):
                self._reconcile_services(job, services, rt_key, spec)

        self.controller.update_job_status(job, replicas, status, restart[0])

        if self.metrics:
            if is_created(old_status) and is_running(status) and not is_running(old_status):
                self.metrics.first_pod_launch_delay(job, active_pods, status)
            if (
                utils.get_total_active_replicas(status.replica_statuses) == total_replicas
                and utils.get_total_active_replicas(old_status.replica_statuses) < total_replicas
                and not is_restarting(old_status)
            ):
                self.metrics.all_pods_launch_delay(job, pods, status)
            self.metrics.observe_status(key, status)

        return self._write_status_and_pace_retry(
            job, status, old_status, key, previous_retry, job_has_new_failure
        )

    def _write_status_and_pace_retry(
        self, job, status, old_status, key: str,
        previous_retry: int, job_has_new_failure: bool,
    ) -> Result:
        """Shared tail of the normal and gang-restart reconcile paths."""
        if status != old_status:
            self._write_status(job, status)
        if job_has_new_failure:
            # Count the failure and pace the retry exponentially; a
            # status-write Conflict requeue deliberately does NOT reach
            # this counter (it raises out of _write_status above).
            self._failure_backoff[key] = previous_retry + 1
            return Result(
                requeue_after=min(
                    BACKOFF_BASE_DELAY_S * (2 ** previous_retry), BACKOFF_MAX_DELAY_S
                )
            )
        return Result()

    # ------------------------------------------------------------------
    # Slice gang restart (net-new; SURVEY.md §5 slice-level health)
    # ------------------------------------------------------------------

    def _gang_failed_retryable(self, replicas, pods: List[Pod]) -> List[Pod]:
        """Failed pods whose replica policy is ExitCode with a retryable code.

        Returns [] when ANY failure is permanent: a deterministic crash on
        one rank tears down its peers with SIGTERM (retryable 143), and a
        gang restart keyed on those peers would delete the evidence and
        loop the slice forever — the normal per-pod path must instead leave
        the permanently-failed pod in place so the job fails."""
        retryable = []
        for rt_key, spec in replicas.items():
            if spec.restart_policy != RestartPolicy.EXIT_CODE:
                continue
            for pod in utils.filter_pods_for_replica_type(pods, rt_key):
                if pod.status.phase != PodPhase.FAILED:
                    continue
                code = self._default_container_exit_code(pod)
                if code != EXIT_CODE_MAGIC and is_retryable_exit_code(code):
                    retryable.append(pod)
                else:
                    # Permanent code OR no observed exit code (eviction,
                    # node loss): the per-pod path treats both as
                    # non-retryable, so the gang path must stand aside too.
                    return []
        return retryable

    def _restart_gang(
        self, job, replicas, status, old_status, pods: List[Pod],
        failed_pods: List[Pod], previous_retry: int, job_has_new_failure: bool,
    ) -> Result:
        """Delete EVERY non-succeeded pod so the slice re-forms atomically.

        A TPU slice admits all-or-nothing and every rank blocks in
        jax.distributed.initialize at startup — restarting only the failed
        index (ref pod.go:296-304) would leave that rank hanging against
        peers that are mid-run. One restart event, not one per pod."""
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        for pod in failed_pods:
            self.recorder.normal(
                job,
                ev.REASON_EXIT_WITH_CODE,
                f"Pod: {pod.metadata.namespace}.{pod.metadata.name} exited "
                f"with code {self._default_container_exit_code(pod)}",
            )
        self.recorder.normal(
            job,
            "SliceRestarting",
            f"Retryable failure in {len(failed_pods)} gang replica(s); "
            f"restarting all replicas so the slice re-forms",
        )
        deleted = 0
        for rt_key in replicas:
            initialize_replica_statuses(status, [rt_key])
            for pod in utils.filter_pods_for_replica_type(pods, rt_key):
                update_job_replica_statuses(status, rt_key, pod)
                if pod.status.phase != PodPhase.SUCCEEDED:
                    self._delete_pod(job, pod)
                    deleted += 1
        job_logger(log, job).info(
            "restarted whole gang (%d of %d pods deleted) after %d retryable failure(s)",
            deleted, len(pods), len(failed_pods),
        )
        if self.metrics:
            self.metrics.restarted_inc()
        self.controller.update_job_status(job, replicas, status, True)
        return self._write_status_and_pace_retry(
            job, status, old_status, key, previous_retry, job_has_new_failure
        )

    # ------------------------------------------------------------------
    # Terminal path (ref job.go:158-204, 321-345)
    # ------------------------------------------------------------------

    def _finalize_job(
        self, job, replicas, status, old_status, run_policy, pods,
        job_exceeds_limit: bool, failure_message: str,
    ) -> Result:
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        self._failure_backoff.pop(key, None)  # terminal: forget backoff state
        self._delete_pods_and_services(run_policy, job, pods)

        result = self._cleanup_job(run_policy, status, job)

        if self.config.enable_gang_scheduling and self.gang is not None:
            self.recorder.normal(job, "JobTerminated", "Job has been terminated. Deleting gang")
            self.gang.delete_gang(job)

        if job_exceeds_limit:
            self.recorder.normal(job, REASON_JOB_FAILED, failure_message)
            if status.completion_time is None:
                status.completion_time = now()
            update_job_conditions(
                status, JobConditionType.FAILED, REASON_JOB_FAILED, failure_message
            )
            if self.metrics:
                self.metrics.failure_inc()

        if is_succeeded(status):
            for rs in status.replica_statuses.values():
                rs.succeeded += rs.active
                rs.active = 0

        if self.metrics:
            key = f"{job.metadata.namespace}/{job.metadata.name}"
            self.metrics.observe_status(key, status)

        if status != old_status:
            self._write_status(job, status)
        return result

    def _delete_pods_and_services(self, run_policy, job, pods: List[Pod]) -> None:
        """Ref job.go:29-52."""
        if not pods:
            return
        policy = run_policy.clean_pod_policy or CleanPodPolicy.RUNNING
        if policy == CleanPodPolicy.NONE:
            return
        for pod in pods:
            if policy == CleanPodPolicy.RUNNING and pod.status.phase != PodPhase.RUNNING:
                continue
            self._delete_pod(job, pod)
            # Pod and service share a name (ref job.go:46-48).
            try:
                self.store.delete("Service", pod.metadata.namespace, pod.metadata.name)
            except NotFound:
                pass

    def _cleanup_job(self, run_policy, status, job) -> Result:
        """TTL-after-finished (ref job.go:321-345)."""
        ttl = run_policy.ttl_seconds_after_finished
        if ttl is None:
            return Result()
        if status.completion_time is None:
            raise RuntimeError(
                f"cleanup job {job.metadata.name}: completion time not set"
            )
        delete_time = status.completion_time + ttl
        current = now()
        if current >= delete_time:
            try:
                self.store.delete(self.controller.kind, job.metadata.namespace, job.metadata.name)
            except NotFound:
                pass
            return Result()
        return Result(requeue_after=delete_time - current)

    # ------------------------------------------------------------------
    # Pod reconcile (ref pod.go:212-310)
    # ------------------------------------------------------------------

    def _reconcile_pods(
        self, job, status: JobStatus, pods: List[Pod], rt: str,
        spec: ReplicaSpec, replicas, restart,
    ) -> None:
        typed_pods = utils.filter_pods_for_replica_type(pods, rt)
        num_replicas = int(spec.replicas or 0)
        initialize_replica_statuses(status, [rt])

        jlog = job_logger(log, job, rtype=rt)
        slices = utils.get_pod_slices(typed_pods, num_replicas)
        for index, pod_slice in enumerate(slices):
            if len(pod_slice) > 1:
                jlog.warning("too many pods for index %d", index)
            elif not pod_slice:
                master_role = self.controller.is_master_role(replicas, rt, index)
                try:
                    self._create_new_pod(job, rt, index, spec, master_role)
                except AlreadyExists:
                    # Terminating leftovers with the same name (ref pod.go:256-279):
                    # repair expectations so the next reconcile isn't gated forever.
                    key = f"{job.metadata.namespace}/{job.metadata.name}"
                    self.expectations.creation_observed(pods_expectation_key(key, rt))
                    self.expectations.creation_observed(services_expectation_key(key, rt))
                    raise
            else:
                pod = pod_slice[0]
                exit_code = self._default_container_exit_code(pod)
                if exit_code != EXIT_CODE_MAGIC:
                    self.recorder.normal(
                        job,
                        ev.REASON_EXIT_WITH_CODE,
                        f"Pod: {pod.metadata.namespace}.{pod.metadata.name} "
                        f"exited with code {exit_code}",
                    )
                if spec.restart_policy == RestartPolicy.EXIT_CODE:
                    if pod.status.phase == PodPhase.FAILED and is_retryable_exit_code(exit_code):
                        job_logger(log, job, rtype=rt, index=index, pod=pod.metadata.name).info(
                            "restarting pod (exit %d)", exit_code
                        )
                        self._delete_pod(job, pod)
                        restart[0] = True
                        if self.metrics:
                            self.metrics.restarted_inc()
                update_job_replica_statuses(status, rt, pod)

    def _create_new_pod(self, job, rt: str, index: int, spec: ReplicaSpec, master_role: bool) -> None:
        """Ref pod.go:312-442."""
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        labels = utils.gen_labels(job.metadata.name)
        labels[LABEL_REPLICA_TYPE] = rt.lower()
        labels[LABEL_REPLICA_INDEX] = str(index)
        if master_role:
            labels[LABEL_JOB_ROLE] = JOB_ROLE_MASTER

        template = copy.deepcopy(spec.template)
        template.metadata.name = utils.gen_general_name(job.metadata.name, rt, index)
        template.metadata.labels.update(labels)

        self.controller.set_cluster_spec(job, template, rt, index)
        for mutate in self.config.pod_mutators:
            mutate(job, template, rt, index, spec)

        if template.spec.restart_policy != PodRestartPolicy.NEVER:
            self.recorder.warning(
                job,
                "SettedPodTemplateRestartPolicy",
                "Restart policy in pod template will be overwritten by restart policy in replica spec",
            )
        # ExitCode is implemented by the controller (delete+recreate), so the
        # pod-level policy maps to Never (ref pod.go:435-442).
        if spec.restart_policy == RestartPolicy.EXIT_CODE or spec.restart_policy is None:
            template.spec.restart_policy = PodRestartPolicy.NEVER
        else:
            template.spec.restart_policy = PodRestartPolicy(spec.restart_policy.value)

        pod = Pod(metadata=copy.deepcopy(template.metadata), spec=copy.deepcopy(template.spec))
        pod.metadata.namespace = job.metadata.namespace
        pod.metadata.owner_references = [self._owner_ref(job)]

        if self.config.enable_gang_scheduling and self.gang is not None:
            self.gang.bind_pod_to_gang(job, pod)

        self.expectations.raise_expectations(pods_expectation_key(key, rt), 1, 0)
        try:
            self.store.create(pod)
        except AlreadyExists:
            self.recorder.warning(job, ev.REASON_FAILED_CREATE_POD, f"pod {pod.metadata.name} already exists")
            raise
        except Exception as e:
            self.expectations.creation_observed(pods_expectation_key(key, rt))
            self.recorder.warning(job, ev.REASON_FAILED_CREATE_POD, f"Error creating: {e}")
            raise
        self.recorder.normal(job, ev.REASON_SUCCESSFUL_CREATE_POD, f"Created pod: {pod.metadata.name}")

    def _default_container_exit_code(self, pod: Pod) -> int:
        """Exit code of the workload's default container, or EXIT_CODE_MAGIC
        when no terminated state has been observed (ref pod.go:285-294)."""
        for cs in pod.status.container_statuses:
            if cs.name == self.controller.default_container_name and cs.terminated:
                return cs.terminated.exit_code
        return EXIT_CODE_MAGIC

    def _delete_pod(self, job, pod: Pod) -> None:
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        rt = pod.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        self.expectations.raise_expectations(pods_expectation_key(key, rt), 0, 1)
        try:
            self.store.delete("Pod", pod.metadata.namespace, pod.metadata.name)
        except NotFound:
            self.expectations.deletion_observed(pods_expectation_key(key, rt))
            return
        except Exception as e:
            self.expectations.deletion_observed(pods_expectation_key(key, rt))
            self.recorder.warning(job, ev.REASON_FAILED_DELETE_POD, f"Error deleting: {e}")
            raise
        self.recorder.normal(job, ev.REASON_SUCCESSFUL_DELETE_POD, f"Deleted pod: {pod.metadata.name}")

    # ------------------------------------------------------------------
    # Service reconcile (ref service.go:188-295)
    # ------------------------------------------------------------------

    def _reconcile_services(self, job, services: List[Service], rt: str, spec: ReplicaSpec) -> None:
        typed = [s for s in services if s.metadata.labels.get(LABEL_REPLICA_TYPE) == rt.lower()]
        num_replicas = int(spec.replicas or 0)
        slices: List[List[Service]] = [[] for _ in range(num_replicas)]
        for svc in typed:
            raw = svc.metadata.labels.get(LABEL_REPLICA_INDEX)
            try:
                index = int(raw) if raw is not None else -1
            except ValueError:
                index = -1
            if 0 <= index < num_replicas:
                slices[index].append(svc)
        for index, svc_slice in enumerate(slices):
            if len(svc_slice) > 1:
                job_logger(log, job, rtype=rt).warning("too many services for index %d", index)
            elif not svc_slice:
                self._create_new_service(job, rt, index, spec)

    def _get_port_from_job(self, spec: ReplicaSpec) -> int:
        """Named port of the default container (ref service.go:221-234)."""
        for container in spec.template.spec.containers:
            if container.name == self.controller.default_container_name:
                port = container.port_named(self.controller.default_port_name)
                if port:
                    return port
        return self.controller.default_port

    def _create_new_service(self, job, rt: str, index: int, spec: ReplicaSpec) -> None:
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        labels = utils.gen_labels(job.metadata.name)
        labels[LABEL_REPLICA_TYPE] = rt.lower()
        labels[LABEL_REPLICA_INDEX] = str(index)
        port = self._get_port_from_job(spec)
        svc = Service(
            spec=ServiceSpec(
                cluster_ip="None",
                selector=dict(labels),
                ports=[ContainerPort(name=self.controller.default_port_name, container_port=port)],
            )
        )
        svc.metadata.name = utils.gen_general_name(job.metadata.name, rt, index)
        svc.metadata.namespace = job.metadata.namespace
        svc.metadata.labels = labels
        svc.metadata.owner_references = [self._owner_ref(job)]

        self.expectations.raise_expectations(services_expectation_key(key, rt), 1, 0)
        try:
            self.store.create(svc)
        except AlreadyExists:
            self.expectations.creation_observed(services_expectation_key(key, rt))
            return
        except Exception as e:
            self.expectations.creation_observed(services_expectation_key(key, rt))
            self.recorder.warning(job, ev.REASON_FAILED_CREATE_SERVICE, f"Error creating: {e}")
            raise
        self.recorder.normal(
            job, ev.REASON_SUCCESSFUL_CREATE_SERVICE, f"Created service: {svc.metadata.name}"
        )

    # ------------------------------------------------------------------
    # Listing + adoption (ref pod.go:166-186, service_ref_manager.go:48-110)
    # ------------------------------------------------------------------

    def _owner_ref(self, job) -> OwnerReference:
        return OwnerReference(
            api_version=self.controller.api_version,
            kind=self.controller.kind,
            name=job.metadata.name,
            uid=job.metadata.uid,
            controller=True,
            block_owner_deletion=True,
        )

    def _selector_matches(self, job, obj) -> bool:
        selector = utils.gen_labels(job.metadata.name)
        return all(obj.metadata.labels.get(k) == v for k, v in selector.items())

    def _can_adopt(self, job) -> bool:
        """Uncached deletion-timestamp recheck before the first adoption
        (ref pkg/job_controller/util.go:33-49 RecheckDeletionTimestamp):
        adopting while the job is being deleted would resurrect orphans."""
        try:
            fresh = read_fresh(
                self.store, self.controller.kind,
                job.metadata.namespace, job.metadata.name,
            )
        except NotFound:
            return False
        return fresh.metadata.deletion_timestamp is None

    def _claim(self, job, objs):
        """Adopt matching orphans / release owned objects whose labels
        drifted (ref pkg/job_controller/service_ref_manager.go:48-110
        ClaimServices semantics, shared by the pod path)."""
        claimed = []
        can_adopt: Optional[bool] = None  # lazily checked, at most once
        for obj in objs:
            matches = self._selector_matches(job, obj)
            ref = obj.metadata.controller_ref()
            if ref is not None:
                if ref.uid != job.metadata.uid:
                    continue  # owned by someone else
                if matches:
                    claimed.append(obj)
                    continue
                # Owned but labels drifted: release so another controller
                # (or nobody) can own it; ignore races — next pass retries.
                obj.metadata.owner_references = [
                    r for r in obj.metadata.owner_references
                    if r.uid != job.metadata.uid
                ]
                try:
                    self.store.update(obj)
                except (Conflict, NotFound):
                    pass
                continue
            if not matches or obj.metadata.deletion_timestamp is not None:
                continue
            if can_adopt is None:
                can_adopt = self._can_adopt(job)
            if not can_adopt:
                continue
            obj.metadata.owner_references.append(self._owner_ref(job))
            try:
                self.store.update(obj)
                claimed.append(obj)
            except (Conflict, NotFound):
                pass
        return claimed

    def get_pods_for_job(self, job) -> List[Pod]:
        # List the whole namespace (not just selector matches) so owned
        # objects whose labels drifted are seen and released.
        pods = self.store.list("Pod", namespace=job.metadata.namespace)
        return self._claim(job, pods)

    def get_services_for_job(self, job) -> List[Service]:
        services = self.store.list("Service", namespace=job.metadata.namespace)
        return self._claim(job, services)

    # ------------------------------------------------------------------
    # Limits (ref job.go:269-319)
    # ------------------------------------------------------------------

    @staticmethod
    def _past_active_deadline(run_policy, status: JobStatus) -> bool:
        if run_policy.active_deadline_seconds is None or status.start_time is None:
            return False
        return now() - status.start_time >= run_policy.active_deadline_seconds

    @staticmethod
    def _past_backoff_limit(run_policy, replicas, pods: List[Pod]) -> bool:
        """Sum restart counts of Running pods for OnFailure/Always replicas."""
        if run_policy.backoff_limit is None:
            return False
        total = 0
        for rt, spec in replicas.items():
            if spec.restart_policy not in (RestartPolicy.ON_FAILURE, RestartPolicy.ALWAYS):
                continue
            for pod in utils.filter_pods_for_replica_type(pods, rt):
                if pod.status.phase != PodPhase.RUNNING:
                    continue
                total += sum(cs.restart_count for cs in pod.status.container_statuses)
        if run_policy.backoff_limit == 0:
            return total > 0
        return total >= run_policy.backoff_limit

    # ------------------------------------------------------------------
    # Status write-back (ref UpdateJobStatusInApiServer impls)
    # ------------------------------------------------------------------

    def _write_status(self, job, status: JobStatus) -> None:
        status.last_reconcile_time = now()
        for _ in range(3):
            try:
                # uncached read: a cache-stale resourceVersion would make
                # every attempt Conflict and burn the retry budget
                fresh = read_fresh(
                    self.store, self.controller.kind,
                    job.metadata.namespace, job.metadata.name,
                )
            except NotFound:
                return
            fresh.status = copy.deepcopy(status)
            try:
                # /status subresource write — a main-path update would be
                # silently dropped by a real apiserver (CRDs declare
                # subresources.status; ref tensorflow/job.go:95-104)
                write_status(self.store, fresh)
                return
            except Conflict:
                continue
        raise Conflict(f"status write for {job.metadata.name} kept conflicting")
