"""Flash attention — Pallas TPU kernels (forward + backward).

The hot op of the flagship model (SURVEY.md §7 step 9). Blocked online-softmax
attention: Q blocks stream against K/V blocks held in VMEM, accumulating in
f32 while inputs stay bf16 so the QK^T and PV matmuls hit the MXU; the
backward pass recomputes P from the saved log-sum-exp instead of
materializing [T, T] attention weights (memory O(T) per block, the property
ring attention builds on — ops/ring_attention.py).

Layout: [batch*heads, seq, head_dim]. The public entry handles GQA by
broadcasting KV heads, pads ragged sequence lengths to block multiples, and
installs a custom VJP wiring the two kernels together.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubedl_tpu.utils.jax_compat import tpu_compiler_params

# Swept on v5e (bf16 MXU inputs, causal fwd): at seq 2048, 512/512 hits
# 53 TF/s vs 47 for 1024/1024 and ~3.5x over 128/128; bigger K/V tiles
# amortize the online-softmax bookkeeping, but past 512 the f32 score
# blocks start crowding the 16 MB scoped VMEM (2048-wide blocks OOM it).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
# Measured crossover on v5e (bf16): the fused kernel loses to plain XLA at
# short sequences (0.26-0.46x at 256-512, where the [T,T] scores are tiny
# and per-program overheads dominate) and wins from ~1024 up (2.6-2.8x).
FLASH_MIN_SEQ = 1024
# Above this sequence length the default kernel's full-K/V-in-VMEM
# BlockSpecs crowd the 16 MB scoped VMEM; the forward streams K/V blocks
# through a 3D grid instead. The backward kernels keep whole-tensor loads,
# so TRAINING beyond this length belongs to ring attention / context
# parallelism — the streamed path serves long-context inference prefill.
STREAM_MIN_SEQ = 8192
NEG_INF = -1e30

_warned_shapes: set = set()


def _warn_unfused_fallback(d: int, block_q: int, block_k: int) -> None:
    """One warning per shape when caller-supplied block sizes are not
    128-aligned and the call silently degrades to unfused attention — a
    masked perf regression otherwise invisible on real TPU. (Head dims are
    lane-aligned by zero-padding, and short sequences dispatch to the
    unfused path by measured policy, neither of which warns.)"""
    key = (d, block_q, block_k)
    if key in _warned_shapes:
        return
    _warned_shapes.add(key)
    import warnings

    warnings.warn(
        f"flash_attention: caller-supplied blocks ({block_q},{block_k}) not "
        f"128-aligned for the TPU MXU; falling back to unfused attention",
        stacklevel=3,
    )


def _interpret() -> bool:
    """Pallas TPU kernels run in interpret mode on CPU (tests/virtual mesh)."""
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _softcap_scores(s, cap):
    """cap * tanh(s / cap) — Gemma-2 logit softcapping, the ONE place
    the transform lives. Backward sites derive its gradient from the
    CAPPED value: d/ds = 1 - tanh(s/cap)^2 = 1 - (capped/cap)^2."""
    return jnp.tanh(s / cap) * cap


def _online_softmax_step(q, k, v, m, l, acc, sm_scale, mask, softcap=None):
    """One K-block update of the online-softmax state (m, l, acc) — the
    shared numerics of the default and streamed forward kernels.
    softcap (Gemma-2): cap*tanh(s/cap) on the scaled scores, applied
    before masking, exactly as in attention_reference."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if softcap is not None:
        s = _softcap_scores(s, softcap)
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                window, block_q, block_k, seq_len, softcap):
    qb = pl.program_id(1)
    # Keep q/k/v in their storage dtype (bf16): the MXU runs bf16 x bf16 ->
    # f32 at full rate, while f32 inputs drop it several-fold. All
    # accumulation stays f32 via preferred_element_type.
    q = q_ref[0]  # [block_q, d]
    head_dim = q.shape[-1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kb = pl.cdiv(seq_len, block_k)
    if causal:
        # K blocks strictly above the diagonal contribute nothing.
        num_kb = jnp.minimum(num_kb, (qb + 1) * block_q // block_k + 1)
    start_kb = jnp.int32(0)
    if window is not None:
        # K blocks entirely below every query's window contribute nothing.
        start_kb = jnp.maximum(0, (qb * block_q - window + 1) // block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask = mask & (k_pos <= q_pos)
        if window is not None:
            mask = mask & (k_pos > q_pos - window)
        return _online_softmax_step(q, k, v, m, l, acc, sm_scale, mask,
                                    softcap)

    m, l, acc = jax.lax.fori_loop(start_kb, num_kb, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lse rides in a [bh, 1, seq] buffer: a (1, 1, block_q) block keeps the
    # trailing two dims TPU-tileable (second-to-last == array dim 1)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fwd(q, k, v, sm_scale, causal, window, block_q, block_k, true_len,
         softcap=None):
    bh, seq, d = q.shape
    # dispatch on the TRUE length: lcm padding of mixed block sizes must
    # not shift the documented threshold
    if true_len > STREAM_MIN_SEQ:
        return _fwd_streamed(q, k, v, sm_scale, causal, window, block_q,
                             block_k, true_len, softcap=softcap)
    grid = (bh, pl.cdiv(seq, block_q))
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal, window=window,
            block_q=block_q, block_k=block_k, seq_len=true_len,
            softcap=softcap,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * seq * seq * d * (0.5 if causal else 1.0)),
            bytes_accessed=q.size * 2 + k.size * 2 + v.size * 2,
            transcendentals=bh * seq * seq,
        ),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


def _fwd_streamed_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s,
                         *, sm_scale, causal, window, block_q, block_k,
                         seq_len, n_kb, softcap):
    """K-streaming variant: grid (bh, q_blocks, k_blocks); K/V arrive one
    block per grid step via BlockSpecs (double-buffered by Mosaic), and the
    online-softmax state lives in VMEM scratch across the kb dimension.
    VMEM use is O(block) regardless of sequence length."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # A 3D grid cannot skip iterations (the K/V DMA always runs), but the
    # compute CAN skip grid steps that contribute nothing: fully past the
    # diagonal (causal) or fully beyond the true sequence. On a causal
    # prefill that's ~half the MXU work.
    live = kb * block_k < seq_len
    if causal:
        live &= kb * block_k < (qb + 1) * block_q
    if window is not None:
        # the whole K block sits below every query's window
        live &= (kb + 1) * block_k - 1 >= qb * block_q - window + 1

    @pl.when(live)
    def _step():
        q = q_ref[0]  # [block_q, d] bf16
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        q_pos = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask = mask & (k_pos <= q_pos)
        if window is not None:
            mask = mask & (k_pos > q_pos - window)
        m_new, l, acc = _online_softmax_step(
            q, k, v, m_s[...], l_s[...], acc_s[...], sm_scale, mask, softcap
        )
        m_s[...] = m_new
        l_s[...] = l
        acc_s[...] = acc

    @pl.when(kb == n_kb - 1)
    def _finish():
        l = jnp.maximum(l_s[...], 1e-30)
        o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_s[...] + jnp.log(l))[:, 0]


def _fwd_streamed(q, k, v, sm_scale, causal, window, block_q, block_k,
                  true_len, softcap=None):
    bh, seq, d = q.shape
    n_kb = pl.cdiv(seq, block_k)
    grid = (bh, pl.cdiv(seq, block_q), n_kb)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_streamed_kernel, sm_scale=sm_scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k,
            seq_len=true_len, n_kb=n_kb, softcap=softcap,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, causal, window, block_q, block_k, seq_len,
                   softcap):
    qb = pl.program_id(1)
    q = q_ref[0]  # bf16 into the MXU; f32 accumulation
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kb = pl.cdiv(seq_len, block_k)
    if causal:
        num_kb = jnp.minimum(num_kb, (qb + 1) * block_q // block_k + 1)
    start_kb = jnp.int32(0)
    if window is not None:
        start_kb = jnp.maximum(0, (qb * block_q - window + 1) // block_k)

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if softcap is not None:
            s = _softcap_scores(s, softcap)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask = mask & (k_pos <= q_pos)
        if window is not None:
            mask = mask & (k_pos > q_pos - window)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if softcap is not None:
            # d/dx[cap*tanh(x/cap)] = 1 - tanh(x/cap)^2 = 1 - (s/cap)^2
            ds = ds * (1.0 - (s / softcap) ** 2)
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(start_kb, num_kb, body, dq0)
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    *, sm_scale, causal, window, block_q, block_k, seq_len,
                    softcap):
    kb = pl.program_id(1)
    k = k_ref[0]  # bf16 into the MXU; f32 accumulation
    v = v_ref[0]
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    num_qb = pl.cdiv(seq_len, block_q)
    start_qb = jnp.int32(0)
    if causal:
        # Q blocks strictly before this K block see none of it.
        start_qb = kb * block_k // block_q
    if window is not None:
        # Q blocks whose every query is past this K block's window.
        num_qb = jnp.minimum(
            num_qb, ((kb + 1) * block_k - 1 + window) // block_q + 1)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if softcap is not None:
            s = _softcap_scores(s, softcap)
        q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask = mask & (k_pos <= q_pos)
        if window is not None:
            mask = mask & (k_pos > q_pos - window)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        pb = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(pb, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if softcap is not None:
            # d/dx[cap*tanh(x/cap)] = 1 - tanh(x/cap)^2 = 1 - (s/cap)^2
            ds = ds * (1.0 - (s / softcap) ** 2)
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros((block_k, v.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (dk0, dv0))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(sm_scale, causal, window, block_q, block_k, true_len, res, dout,
         softcap=None):
    q, k, v, out, lse = res
    bh, seq, d = q.shape
    # [bh, 1, seq] to match the lse layout (TPU-tileable blocks)
    delta = jnp.sum(
        out.astype(jnp.float32) * dout.astype(jnp.float32), axis=-1
    )[:, None, :]

    kern = dict(sm_scale=sm_scale, causal=causal, window=window,
                block_q=block_q, block_k=block_k, seq_len=true_len,
                softcap=softcap)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kern),
        grid=(bh, pl.cdiv(seq, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kern),
        grid=(bh, pl.cdiv(seq, block_k)),
        in_specs=[
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


def _pad_d(x, dk):
    pad = dk - x.shape[-1]
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, sm_scale, causal, window, block_q, block_k, true_len,
           true_d, softcap):
    out, _ = _fwd(q, k, v, sm_scale, causal, window, block_q, block_k,
                  true_len, softcap=softcap)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, window, block_q, block_k, true_len,
               true_d, softcap):
    out, lse = _fwd(q, k, v, sm_scale, causal, window, block_q, block_k,
                    true_len, softcap=softcap)
    # Residuals store only the true head dim: padded columns are zeros by
    # construction, so slicing here and re-padding in backward is exact —
    # and halves attention residual HBM for d=64 models.
    res = (
        q[..., :true_d], k[..., :true_d], v[..., :true_d],
        out[..., :true_d], lse,
    )
    return out, res


# Bound at import (NOT an alias of the monkeypatchable dispatch knob): the
# backward kernels load whole-sequence tensors into VMEM and cannot fit
# beyond this — training longer sequences is context parallelism's job.
BWD_MAX_SEQ = 8192


def _flash_bwd(sm_scale, causal, window, block_q, block_k, true_len, true_d,
               softcap, res, dout):
    dk_width = dout.shape[-1]
    q, k, v, out, lse = res
    if true_len > BWD_MAX_SEQ:
        raise ValueError(
            f"flash_attention backward at seq {true_len} exceeds the "
            f"kernel's whole-sequence VMEM budget (max {BWD_MAX_SEQ}); "
            f"train long sequences with ring attention over a 'context' "
            f"mesh axis (ops/ring_attention.py) — the streamed forward "
            f"serves inference prefill only"
        )
    res = (
        _pad_d(q, dk_width), _pad_d(k, dk_width), _pad_d(v, dk_width),
        _pad_d(out, dk_width), lse,
    )
    return _bwd(sm_scale, causal, window, block_q, block_k, true_len, res,
                dout, softcap=softcap)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _snap_block(block: int) -> int:
    """Largest divisor of STREAM_MIN_SEQ that is <= block; sub-128 blocks
    (interpret mode only) pass through untouched."""
    if block < 128 or STREAM_MIN_SEQ % block == 0:
        return block
    p = 128
    while p * 2 <= min(block, STREAM_MIN_SEQ):
        p *= 2
    return p


def _pad_seq_to(x, target):
    pad = target - x.shape[1]
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    min_seq: Optional[int] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """Blocked attention over [batch, q_heads, seq, head_dim] tensors.

    GQA: k/v may have fewer heads (q_heads % kv_heads == 0); KV heads are
    broadcast to the query groups.

    window: sliding-window (Mistral-style) attention — query i attends
    keys in (i - window, i]. Requires causal=True. Dead K blocks are
    skipped in both directions, so compute scales with window, not seq.

    softcap (Gemma-2): cap*tanh(s/cap) on the scaled scores before
    masking, applied inside the kernel (forward AND the custom VJP —
    the backward multiplies dS by 1 - (s_capped/cap)^2).

    min_seq overrides the measured fused-vs-unfused crossover (default
    FLASH_MIN_SEQ, swept on v5e): pass 0 to prefer the fused kernel at
    any length — e.g. on a different TPU generation, or when the kernel's
    O(T)-per-block memory (not its speed) is the point. Sequences shorter
    than one 128 lane tile cannot tile onto the MXU and always take the
    unfused path.
    """
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding window "
                             "is a causal-attention concept)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if softcap is not None and softcap <= 0:
        raise ValueError(f"softcap must be > 0 or None, got {softcap}")
    if hq != hkv:
        if hq % hkv:
            raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)

    # Below the measured crossover the unfused path is simply faster —
    # this is dispatch policy, not degradation (no warning). Interpret
    # mode (CPU tests) keeps exercising the kernel at small shapes.
    if min_seq is None:
        min_seq = FLASH_MIN_SEQ
    # < 128 can never tile onto the MXU regardless of min_seq (silent: it's
    # a hardware constraint, not a degradation a caller could fix)
    if not _interpret() and (sq < min_seq or sq < 128):
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                                   window=window, softcap=softcap)

    # Lane-align the head dim by zero-padding to the next multiple of 128
    # (ViT-class 64, GQA oddballs): zero K columns add nothing to QK^T,
    # zero V columns produce zero output columns that are sliced off, and
    # autodiff through pad/slice keeps the VJP exact. At the sequence
    # lengths that reach here (>= FLASH_MIN_SEQ) the extra MXU work still
    # beats the unfused path's materialized [T, T] softmax (2.65x at
    # s=1024 d=64 on v5e).
    d_pad = (-d) % 128
    if d_pad:
        widen = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        q = jnp.pad(q, widen)
        k = jnp.pad(k, widen)
        v = jnp.pad(v, widen)
    dk = d + d_pad

    # Clamp blocks to the sequence, keeping them lane-aligned (128) so
    # mid-size sequences stay on the fused kernel (padding fills the rest).
    if sq >= 128:
        cap = (sq // 128) * 128
        block_q = min(block_q, cap)
        block_k = min(block_k, cap)
    else:
        block_q = block_k = max(sq, 1)

    # Mosaic requires MXU-tileable blocks on real TPU: short sequences
    # (< 128) take the plain-XLA path — at those sizes the fused kernel
    # has no advantage anyway. CPU interpret mode is exempt.
    if not _interpret() and (block_q % 128 or block_k % 128):
        _warn_unfused_fallback(d, block_q, block_k)
        return attention_reference(
            q[..., :d], k[..., :d], v[..., :d], causal=causal,
            sm_scale=sm_scale, window=window, softcap=softcap,
        )

    # The whole-sequence kernels (fwd at <= STREAM_MIN_SEQ, bwd always)
    # budget VMEM for a padded length of at most STREAM_MIN_SEQ. Exotic
    # block sizes (640, 384, ...) have lcms that can pad PAST that budget
    # even when the true length is under it; only then snap them down to
    # divisors of STREAM_MIN_SEQ (all its divisors are pow2 multiples of
    # 128), which bounds the padded length by the budget again. In-budget
    # caller choices are preserved exactly.
    if sq <= STREAM_MIN_SEQ:
        lcm0 = math.lcm(block_q, block_k)
        if pl.cdiv(sq, lcm0) * lcm0 > STREAM_MIN_SEQ:
            block_q = _snap_block(block_q)
            block_k = _snap_block(block_k)

    # One COMMON padded length divisible by both blocks: padding q and k/v
    # to different lengths would send the K-block grid out of bounds when
    # block_q != block_k. The padded tail is masked via seq_len.
    lcm = math.lcm(block_q, block_k)
    target = pl.cdiv(sq, lcm) * lcm
    qf = _pad_seq_to(q.reshape(b * hq, sq, dk), target)
    kf = _pad_seq_to(k.reshape(b * hq, sq, dk), target)
    vf = _pad_seq_to(v.reshape(b * hq, sq, dk), target)
    out = _flash(qf, kf, vf, sm_scale, causal, window, block_q, block_k,
                 sq, d, softcap)
    return out[:, :sq, :d].reshape(b, hq, sq, d)


def attention_reference(q, k, v, *, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        window: Optional[int] = None,
                        softcap: Optional[float] = None):
    """Plain-XLA attention for correctness tests and softcapped configs
    (same GQA semantics, incl. the sliding window; softcap applies
    Gemma-2's cap*tanh(s/cap) to the scaled scores before masking)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    if sm_scale is None:
        sm_scale = 1.0 / (d**0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    if softcap is not None:
        s = _softcap_scores(s, softcap)
    if causal:
        mask = np.tril(np.ones((sq, sq), bool))
        if window is not None:
            mask &= ~np.tril(np.ones((sq, sq), bool), k=-window)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
