"""Ulysses-style sequence parallelism — all-to-all head<->sequence swap.

The second of the two long-context strategies (alongside
ops/ring_attention.py; the reference has neither — SURVEY §5
"long-context: entirely absent"). Where ring attention keeps queries
local and ROTATES K/V around the mesh (P-1 ppermute hops overlapped
with compute), Ulysses runs TWO all-to-alls: the sequence-sharded
[b, h, t/P, d] projections swap into head-sharded [b, h/P, t, d], each
rank computes ordinary full-sequence attention for its head group (the
flash kernel applies unchanged), and one all-to-all swaps back.

Trade-off (why both exist): Ulysses moves each token's Q,K,V,O exactly
once (4 all-to-alls of 1/P-sized tensors) regardless of sequence length
— cheaper than the ring when P is small and heads are plentiful — but
its parallelism is capped at n_kv_heads and the full-sequence scores
live on one rank; the ring scales to any P and keeps score memory at
t/P per rank. Both ride the ICI `context` axis placed innermost by
AXIS_ORDER (parallel/mesh.py).

Public entry matches ring_attention's, so models swap strategies by
name (LlamaConfig.context_parallel = "ring" | "ulysses").
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubedl_tpu.utils.jax_compat import shard_map


def _ulysses_sharded(q, k, v, *, axis_name, sm_scale, causal, use_flash):
    """Runs inside shard_map: q/k/v are [b, h, t_local, d] seq shards."""
    def seq_to_heads(x):
        # [b, h, t/P, d] -> [b, h/P, t, d]: split heads, gather sequence
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from kubedl_tpu.ops.flash_attention import flash_attention

        o = flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    else:
        from kubedl_tpu.ops.flash_attention import attention_reference

        o = attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(o)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    use_flash: bool = False,
    q_spec: P = P(("data", "fsdp"), "tensor", "context", None),
) -> jax.Array:
    """Sequence-parallel attention over [batch, heads, seq, head_dim]
    with the seq dim sharded over `axis_name`.

    Heads must divide by the context-axis size (after any tensor-axis
    head sharding) — Ulysses' parallelism lives in the head dimension.
    GQA broadcast must happen in the caller (models/llama.py does), so
    K/V enter with the same head count as Q.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    ctx = mesh.shape.get(axis_name, 1)
    heads = q.shape[1]
    tensor = mesh.shape.get("tensor", 1)
    local_heads = heads // max(tensor, 1)
    if local_heads % ctx != 0:
        raise ValueError(
            f"ulysses needs heads-per-tensor-shard ({local_heads}) divisible "
            f"by the context axis ({ctx}); use ring attention instead")
    fn = functools.partial(
        _ulysses_sharded, axis_name=axis_name, sm_scale=sm_scale,
        causal=causal, use_flash=use_flash,
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(q_spec, q_spec, q_spec), out_specs=q_spec,
    )(q, k, v)
