"""Ring attention — context-parallel attention over the mesh "context" axis.

Long-context path (SURVEY.md §5/§7 step 9 — entirely absent in the
reference): the sequence is sharded across devices; K/V chunks rotate around
the ring with jax.lax.ppermute (XLA lowers to ICI neighbor transfers —
the slice admitter places consecutive ranks on ICI-adjacent hosts via
executor/tpu_topology.ring_order), while each device's Q stays resident.
Per-chunk partial attentions merge through their log-sum-exp, so softmax
normalization is exact regardless of arrival order.

Implementation notes:
  * the per-step chunk attention is wrapped in jax.checkpoint so autodiff
    recomputes the [Tq_local, Tk_chunk] scores instead of saving c of them —
    activation memory stays O(T/c * d) per device;
  * communication overlaps compute: ppermute of the NEXT chunk is issued
    alongside the CURRENT chunk's attention inside one lax.scan step, and
    XLA schedules the transfer behind the matmuls;
  * causal masking is by global position: chunks entirely in the future are
    skipped via a zero-weight merge (lse = -inf), the diagonal chunk gets a
    triangular mask.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubedl_tpu.utils.jax_compat import shard_map

NEG_INF = -1e30


def _chunk_attention(q, k, v, sm_scale, causal_mode, q_offset, k_offset):
    """Partial attention of local Q against one K/V chunk.

    causal_mode: 0 = full (chunk entirely in the past), 1 = diagonal
    (triangular mask), 2 = skip (entirely in the future).
    Returns (out [b,h,tq,d] f32, lse [b,h,tq] f32).
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    # bf16 inputs straight into the MXU (full-rate); f32 accumulation via
    # preferred_element_type — casting to f32 first would run the MXU at
    # its reduced f32 rate.
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    diag_mask = k_pos <= q_pos
    mask = jnp.where(
        causal_mode == 1,
        diag_mask,
        jnp.full_like(diag_mask, True),
    )
    mask = jnp.where(causal_mode == 2, jnp.zeros_like(mask), mask)
    s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [b,h,tq]
    # fully-masked rows: keep exp() finite
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    lse = jnp.where(l > 0, m_safe + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    out = jnp.where(l[..., None] > 0, out / jnp.maximum(l[..., None], 1e-30), 0.0)
    return out, lse


def _merge(o1, lse1, o2, lse2):
    """Merge two partial attentions via their log-sum-exp."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.maximum(m, NEG_INF / 2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    tot = jnp.maximum(w1 + w2, 1e-30)
    out = (o1 * w1[..., None] + o2 * w2[..., None]) / tot[..., None]
    lse = m + jnp.log(tot)
    return out, lse


def _ring_attention_sharded(q, k, v, *, axis_name, sm_scale, causal):
    """Runs inside shard_map: q/k/v are the LOCAL sequence chunks."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, tq, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    o0 = jnp.zeros((b, h, tq, d), jnp.float32)
    lse0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)

    @jax.checkpoint
    def chunk_step(q, k, v, kv_idx):
        if causal:
            mode = jnp.where(kv_idx < my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2))
        else:
            mode = jnp.zeros((), jnp.int32)
        return _chunk_attention(
            q, k, v, sm_scale, mode, my_idx * tq, kv_idx * tq
        )

    def scan_body(carry, step):
        o, lse, k_cur, v_cur = carry
        kv_idx = (my_idx - step) % axis_size
        o_c, lse_c = chunk_step(q, k_cur, v_cur, kv_idx)
        o, lse = _merge(o, lse, o_c, lse_c)
        # rotate KV to the next rank; XLA overlaps this with the matmuls
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    (o, lse, _, _), _ = jax.lax.scan(
        scan_body, (o0, lse0, k, v), jnp.arange(axis_size)
    )
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_spec: P = P(("data", "fsdp"), "tensor", "context", None),
) -> jax.Array:
    """Context-parallel attention over [batch, heads, seq, head_dim].

    The seq dimension is sharded over `axis_name`; batch/heads follow
    `q_spec`. GQA broadcast should be done by the caller (models/llama.py
    does) so the ring rotates the small KV tensors.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    fn = functools.partial(
        _ring_attention_sharded, axis_name=axis_name, sm_scale=sm_scale, causal=causal
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(q_spec, q_spec, q_spec), out_specs=q_spec,
    )(q, k, v)
