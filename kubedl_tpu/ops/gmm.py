"""Grouped matrix multiply — the dropless-MoE expert FFN kernel.

`gmm(lhs, rhs, tile_expert)` computes, for every row-tile i of `lhs`,
`lhs[i] @ rhs[tile_expert[i]]` — i.e. a matmul whose weight matrix
changes per row-group. This is the TPU-native alternative to both of
the classic MoE dispatch shapes:

  * GShard's dense one-hot einsums burn S*E*C*d FLOPs per dispatch —
    measured equal to the expert FFN compute itself (models/moe.py);
  * capacity-slot gather/scatter (models/moe.py today) is
    bandwidth-cheap but still RUNS the expert matmuls over every
    capacity slot: at capacity_factor 1.25 that is a hard 1/1.25
    ceiling on MFU (the committed 0.474 at dense 0.60 is exactly that
    ceiling).

Here tokens are sorted by expert and padded per group to the row-tile
size, so the expert matmuls touch `top_k*S + E*tile_m` rows — a few
percent of tile rounding instead of 25% capacity padding, and NO
dropped tokens.

Three kernel families share the mechanics (ref: the megablox `gmm`
pattern from public JAX — SNIPPETS.md has no counterpart; built from
the pallas guide):
  * `gmm` — the plain grouped matmul;
  * `gmm_scaled` — same, with a per-expert [E, N] output scale folded
    into the accumulator flush (int8 per-output-channel dequant without
    materializing [M, N] row-scale arrays host-side);
  * `gmm_swiglu` — the fused MoE FFN front half: TWO weight stacks per
    tile, `silu(x @ w1_e * s1_e) * (x @ w3_e * s3_e)` computed in the
    f32 accumulators before a single write-back. Collapses the three
    unfused launches' first two and removes two [M, ffn] HBM
    round-trips (gate and up never hit HBM separately).

Shared mechanics:
  * caller guarantees every row-tile belongs to exactly ONE group and
    passes `tile_expert[num_m_tiles]`; the scalar-prefetch grid spec
    lets the rhs/scale BlockSpec index_maps select the expert's blocks
    per tile before the kernel body runs;
  * grid (m_tiles, n_tiles, k_tiles), k innermost sequential; f32
    accumulator scratch, epilogue (scale / SwiGLU) on the last k step;
  * tile sizes are dtype-aware (`_pick_tiles`): narrower element types
    take wider k/n tiles — the VMEM block budget stays ~constant while
    each block amortizes more MXU work per HBM fetch;
  * backward: dlhs is the same gmm against rhs^T (per expert);
    drhs is `tgmm` — grid (k, n, m) with m innermost sequential,
    accumulating row-tiles into the owning expert's [K, N] block
    (zeroed on the group's first tile). `gmm_swiglu` recomputes the
    two pre-activation products in backward (flash-attention-style
    rematerialization) rather than saving them.

Like ops/flash_attention.py, kernels run in interpret mode off-TPU so
CPU tests exercise the real kernel logic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubedl_tpu.utils.jax_compat import tpu_compiler_params

TILE_M = 128
_TILE_N = 256
_TILE_K = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row_tile_of(m: int, tile_expert, name: str) -> int:
    """The row-tile size IS m / len(tile_expert): the caller's per-tile
    expert map fixes the granularity, so bigger row tiles need no extra
    argument — the dispatch layout (moe.py `_row_tile`) simply hands in
    fewer, wider tiles. Bigger tiles matter: the kernel streams each
    row-tile's full [K, N] weight block from HBM, so rhs traffic is
    (m / tile) * K * N bytes — at tile 128 that is ~128 flops per rhs
    byte, BELOW a v5e's ~240 flops/byte balance point (the measured
    ~0.5x MoE-vs-dense efficiency gap); tile 512 clears it with margin.
    Must stay a multiple of TILE_M (layout padding + MXU sublanes)."""
    n_tiles = int(tile_expert.shape[0])
    if n_tiles <= 0 or m % n_tiles:
        raise ValueError(
            f"{name} tile_expert has {n_tiles} entries which do not evenly "
            f"tile {m} lhs rows; a ragged tail would silently never be "
            "computed")
    tm = m // n_tiles
    if tm % TILE_M:
        raise ValueError(
            f"{name} row-tile {tm} ({m} rows / {n_tiles} tile entries) "
            f"must be a multiple of TILE_M ({TILE_M}); the grid covers "
            "whole tiles and a ragged tail would silently never be "
            "computed")
    return tm


def _pick(dim: int, pref: int) -> int:
    """Largest tile <= pref that divides dim (dims here are model sizes —
    multiples of 128 in practice; fall back to the dim itself)."""
    for t in (pref, 512, 256, 128):
        if t <= pref and dim % t == 0:
            return t
    return dim


def _pick_tiles(k: int, n: int, dtype) -> "tuple[int, int]":
    """Dtype-aware (tk, tn): per-block VMEM bytes stay ~flat as elements
    narrow, so bf16/int8 take wider tiles — each weight block fetched
    from HBM feeds proportionally more MXU work. f32 keeps the classic
    256x256; 2-byte types go 512 on both contraction and output dims
    (block set ~1 MB + f32 accumulators, comfortably inside 16 MB VMEM
    with double buffering); 1-byte types the same (the MXU computes in
    bf16 after the operand-read convert, so wider than 512 buys nothing
    once accumulators dominate)."""
    itemsize = jnp.dtype(dtype).itemsize
    if itemsize >= 4:
        pk, pn = _TILE_K, _TILE_N
    else:
        pk, pn = 512, 512
    return _pick(k, pk), _pick(n, pn)


# -- forward -----------------------------------------------------------------


def _gmm_kernel(te_ref, lhs_ref, rhs_ref, out_ref, acc_ref, *, nk):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(
        lhs_ref[...], rhs_ref[0],
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nk - 1)
    def _flush():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _gmm_scaled_kernel(te_ref, lhs_ref, rhs_ref, scale_ref, out_ref, acc_ref,
                       *, nk):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(
        lhs_ref[...], rhs_ref[0],
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nk - 1)
    def _flush():
        # per-expert per-output-channel scale folded in the epilogue —
        # the [tn] vector broadcasts over the tile's rows, so no [M, N]
        # scale array ever exists in HBM
        out_ref[...] = (
            acc_ref[...] * scale_ref[0].astype(jnp.float32)
        ).astype(out_ref.dtype)


def _gmm_swiglu_kernel(te_ref, lhs_ref, w1_ref, w3_ref, s1_ref, s3_ref,
                       out_ref, acc1_ref, acc3_ref, *, nk):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc1_ref[...] = jnp.zeros_like(acc1_ref)
        acc3_ref[...] = jnp.zeros_like(acc3_ref)

    acc1_ref[...] += jnp.dot(
        lhs_ref[...], w1_ref[0], preferred_element_type=jnp.float32)
    acc3_ref[...] += jnp.dot(
        lhs_ref[...], w3_ref[0], preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _flush():
        # SwiGLU in the f32 accumulators: silu(gate) * up computed
        # before the single write-back — gate and up never round-trip
        # HBM as separate [M, ffn] tensors
        gate = acc1_ref[...] * s1_ref[0].astype(jnp.float32)
        up = acc3_ref[...] * s3_ref[0].astype(jnp.float32)
        out_ref[...] = (jax.nn.silu(gate) * up).astype(out_ref.dtype)


def _gmm_raw(lhs, rhs, tile_expert, out_scale=None):
    m, k = lhs.shape
    _, _, n = rhs.shape
    tm = _row_tile_of(m, tile_expert, "gmm")
    tk, tn = _pick_tiles(k, n, lhs.dtype)
    nk = k // tk
    grid = (m // tm, n // tn, nk)
    if out_scale is None:
        kernel = functools.partial(_gmm_kernel, nk=nk)
        in_specs = [
            pl.BlockSpec((tm, tk), lambda i, j, kk, te: (i, kk)),
            pl.BlockSpec((1, tk, tn), lambda i, j, kk, te: (te[i], kk, j)),
        ]
        operands = (tile_expert, lhs, rhs)
    else:
        kernel = functools.partial(_gmm_scaled_kernel, nk=nk)
        in_specs = [
            pl.BlockSpec((tm, tk), lambda i, j, kk, te: (i, kk)),
            pl.BlockSpec((1, tk, tn), lambda i, j, kk, te: (te[i], kk, j)),
            pl.BlockSpec((1, tn), lambda i, j, kk, te: (te[i], j)),
        ]
        operands = (tile_expert, lhs, rhs, out_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk, te: (i, j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n, bytes_accessed=0, transcendentals=0),
        interpret=_interpret(),
    )(*operands)


def _gmm_swiglu_raw(lhs, w1, w3, tile_expert, scale1, scale3):
    m, k = lhs.shape
    _, _, n = w1.shape

    if w3.shape != w1.shape:
        raise ValueError(f"w1 {w1.shape} vs w3 {w3.shape} shape mismatch")
    tm = _row_tile_of(m, tile_expert, "gmm_swiglu")
    tk, tn = _pick_tiles(k, n, lhs.dtype)
    nk = k // tk
    grid = (m // tm, n // tn, nk)
    return pl.pallas_call(
        functools.partial(_gmm_swiglu_kernel, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda i, j, kk, te: (i, kk)),
                pl.BlockSpec((1, tk, tn), lambda i, j, kk, te: (te[i], kk, j)),
                pl.BlockSpec((1, tk, tn), lambda i, j, kk, te: (te[i], kk, j)),
                pl.BlockSpec((1, tn), lambda i, j, kk, te: (te[i], j)),
                pl.BlockSpec((1, tn), lambda i, j, kk, te: (te[i], j)),
            ],
            out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk, te: (i, j)),
            scratch_shapes=[
                pltpu.VMEM((tm, tn), jnp.float32),
                pltpu.VMEM((tm, tn), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * m * k * n, bytes_accessed=0, transcendentals=m * n),
        interpret=_interpret(),
    )(tile_expert, lhs, w1, w3, scale1, scale3)


# -- transposed (weight-gradient) --------------------------------------------


def _tgmm_kernel(te_ref, first_ref, lhs_ref, dout_ref, out_ref):
    mm = pl.program_id(2)

    @pl.when(first_ref[mm] == 1)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += jnp.dot(
        lhs_ref[...].T, dout_ref[...],
        preferred_element_type=jnp.float32,
    )[None]


def _tgmm_raw(lhs, dout, tile_expert, first_tile, n_experts):
    """drhs[e] = sum over e's row-tiles of lhs_tile^T @ dout_tile.
    Experts with no tiles keep whatever was in their block — callers
    mask them to zero (cheap jnp.where on group counts)."""
    m, k = lhs.shape
    _, n = dout.shape
    tm = _row_tile_of(m, tile_expert, "tgmm")
    tk, tn = _pick_tiles(k, n, lhs.dtype)
    grid = (k // tk, n // tn, m // tm)
    return pl.pallas_call(
        _tgmm_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda kk, j, i, te, fi: (i, kk)),
                pl.BlockSpec((tm, tn), lambda kk, j, i, te, fi: (i, j)),
            ],
            out_specs=pl.BlockSpec(
                (1, tk, tn), lambda kk, j, i, te, fi: (te[i], kk, j)),
            scratch_shapes=[],
        ),
        out_shape=jax.ShapeDtypeStruct((n_experts, k, n), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n, bytes_accessed=0, transcendentals=0),
        interpret=_interpret(),
    )(tile_expert, first_tile, lhs, dout)


# -- shared backward helpers -------------------------------------------------


def _owned_mask(tile_expert, n_experts):
    """[E] int32 count of row-tiles each expert owns (0 = never written
    by tgmm — its block is garbage and must be masked)."""
    return jnp.zeros((n_experts,), jnp.int32).at[tile_expert].add(
        1, mode="drop")


def _bcast_tile_scale(x, scale, tile_expert):
    """x[m, n] * scale[tile_expert][...] without materializing a [m, n]
    repeat array: the per-tile [n] vectors broadcast over a reshaped
    [tiles, row_tile, n] view (XLA fuses the whole thing)."""
    m, n = x.shape
    nt = tile_expert.shape[0]
    return (
        x.reshape(nt, m // nt, n)
        * scale[tile_expert][:, None, :].astype(x.dtype)
    ).reshape(m, n)


def _tile_segsum(x, tile_expert, n_experts):
    """[E, N] per-expert sum of x's rows (x [m, n]) — the dscale
    reduction: each tile's rows collapse, then tiles scatter-add into
    their owning expert's row."""
    m, n = x.shape
    nt = tile_expert.shape[0]
    per_tile = x.reshape(nt, m // nt, n).sum(axis=1)
    return jnp.zeros((n_experts, n), x.dtype).at[tile_expert].add(
        per_tile, mode="drop")


def _first_tile_flags(tile_expert):
    """1 where a tile starts a new expert run (m-order), else 0."""
    prev = jnp.concatenate(
        [jnp.full((1,), -1, tile_expert.dtype), tile_expert[:-1]])
    return (tile_expert != prev).astype(jnp.int32)


def _drhs(lhs, dout, tile_expert, n_experts):
    first = _first_tile_flags(tile_expert)
    drhs = _tgmm_raw(lhs, dout, tile_expert, first, n_experts)
    owned = _owned_mask(tile_expert, n_experts)
    return jnp.where((owned > 0)[:, None, None], drhs, 0.0)


# -- public ops with VJPs ----------------------------------------------------


@jax.custom_vjp
def _gmm_vjp(lhs, rhs, tile_expert):
    return _gmm_raw(lhs, rhs, tile_expert)


def _gmm_fwd(lhs, rhs, tile_expert):
    return _gmm_raw(lhs, rhs, tile_expert), (lhs, rhs, tile_expert)


def _gmm_bwd(res, dout):
    lhs, rhs, tile_expert = res
    dlhs = _gmm_raw(dout, jnp.swapaxes(rhs, 1, 2), tile_expert)
    drhs = _drhs(lhs, dout, tile_expert, rhs.shape[0])
    dte = np.zeros(tile_expert.shape, jax.dtypes.float0)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), dte


_gmm_vjp.defvjp(_gmm_fwd, _gmm_bwd)


def _check_row_tile(m: int, tile_expert, row_tile: int, name: str) -> None:
    """Public-entry validation: the caller states the row-tile size it
    laid the rows out with, and len(tile_expert) must agree — otherwise
    a truncated tile_expert whose length happens to divide m would be
    silently reinterpreted as a wider tile and apply one expert's
    weights to another's rows."""
    if row_tile % TILE_M:
        raise ValueError(
            f"{name} row_tile {row_tile} must be a multiple of TILE_M "
            f"({TILE_M}) — MXU sublane alignment")
    if m % row_tile:
        raise ValueError(
            f"{name} lhs rows ({m}) must be a multiple of TILE_M-aligned "
            f"row_tile {row_tile}; the grid covers m // row_tile tiles and "
            "a ragged tail would silently never be computed")
    if tile_expert.shape[0] != m // row_tile:
        raise ValueError(
            f"{name} tile_expert has {tile_expert.shape[0]} entries for "
            f"{m // row_tile} row-tiles of {row_tile} rows; an out-of-range "
            "te[i] gather clamps and would silently reuse the last "
            "expert's weights")


def gmm(lhs, rhs, tile_expert, *, row_tile: int = TILE_M):
    """[M, K] x [E, K, N] -> [M, N], weight chosen per row-tile.

    `tile_expert[i]` names the expert for row-tile i (rows sorted and
    per-group padded to `row_tile` by the caller — see moe.py's
    dropless dispatch, which uses wider tiles for large dispatches to
    amortize the per-tile weight stream). Padding rows are zeros; they
    multiply into zeros and are never gathered back."""
    _check_row_tile(lhs.shape[0], tile_expert, row_tile, "gmm")
    return _gmm_vjp(lhs, rhs, tile_expert)


@jax.custom_vjp
def _gmm_scaled_vjp(lhs, rhs, tile_expert, out_scale):
    return _gmm_raw(lhs, rhs, tile_expert, out_scale=out_scale)


def _gmm_scaled_fwd(lhs, rhs, tile_expert, out_scale):
    out = _gmm_raw(lhs, rhs, tile_expert, out_scale=out_scale)
    return out, (lhs, rhs, tile_expert, out_scale, out)


def _gmm_scaled_bwd(res, dout):
    lhs, rhs, tile_expert, out_scale, out = res
    e = rhs.shape[0]
    # y = raw * s  =>  dL/draw = dout * s (tile-broadcast, no repeat)
    dpre = _bcast_tile_scale(dout, out_scale, tile_expert)
    dlhs = _gmm_raw(dpre, jnp.swapaxes(rhs, 1, 2), tile_expert)
    drhs = _drhs(lhs, dpre, tile_expert, e)
    # dL/ds[e, n] = sum over e's rows of raw * dout. raw = out / s (s is
    # strictly positive by construction, quant.py) and s is constant per
    # (e, n) within a segment, so the division moves OUTSIDE the segsum
    # — no forward-sized rematerialization launch needed
    dscale = _tile_segsum(
        out.astype(jnp.float32) * dout.astype(jnp.float32), tile_expert, e
    ) / out_scale.astype(jnp.float32)
    dte = np.zeros(tile_expert.shape, jax.dtypes.float0)
    return (dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), dte,
            dscale.astype(out_scale.dtype))


_gmm_scaled_vjp.defvjp(_gmm_scaled_fwd, _gmm_scaled_bwd)


def gmm_scaled(lhs, rhs, tile_expert, out_scale, *, row_tile: int = TILE_M):
    """gmm with a per-expert output scale: out[i] = (lhs[i] @
    rhs[te[i]]) * out_scale[te[i]], the scale ([E, N], per output
    channel) folded into the kernel epilogue. This is the int8 dequant
    path: the alternative — gathering scale rows host-side — builds a
    [M, N] f32 array whose size scales with the per-expert tile padding
    (e * row_tile extra rows), a pure memory/bandwidth tax."""
    _check_row_tile(lhs.shape[0], tile_expert, row_tile, "gmm_scaled")
    return _gmm_scaled_vjp(lhs, rhs, tile_expert, out_scale)


@jax.custom_vjp
def _gmm_swiglu_vjp(lhs, w1, w3, tile_expert, scale1, scale3):
    return _gmm_swiglu_raw(lhs, w1, w3, tile_expert, scale1, scale3)


def _gmm_swiglu_fwd(lhs, w1, w3, tile_expert, scale1, scale3):
    out = _gmm_swiglu_raw(lhs, w1, w3, tile_expert, scale1, scale3)
    return out, (lhs, w1, w3, tile_expert, scale1, scale3)


def _gmm_swiglu_bwd(res, dout):
    lhs, w1, w3, tile_expert, scale1, scale3 = res
    e = w1.shape[0]
    # rematerialize the pre-activation products (flash-style: cheaper
    # than holding two [M, ffn] tensors across the backward)
    g_raw = _gmm_raw(lhs, w1, tile_expert)
    u_raw = _gmm_raw(lhs, w3, tile_expert)
    g = _bcast_tile_scale(g_raw, scale1, tile_expert).astype(jnp.float32)
    u = _bcast_tile_scale(u_raw, scale3, tile_expert).astype(jnp.float32)
    df = dout.astype(jnp.float32)
    sig = jax.nn.sigmoid(g)
    silu_g = g * sig
    # d silu(g)/dg = sig * (1 + g * (1 - sig))
    dgate = df * u * (sig * (1.0 + g * (1.0 - sig)))
    dup = df * silu_g
    # fold the forward scales into the upstream grads (tile-broadcast)
    dgate_pre = _bcast_tile_scale(
        dgate.astype(lhs.dtype), scale1, tile_expert)
    dup_pre = _bcast_tile_scale(dup.astype(lhs.dtype), scale3, tile_expert)
    dlhs = (
        _gmm_raw(dgate_pre, jnp.swapaxes(w1, 1, 2), tile_expert)
        + _gmm_raw(dup_pre, jnp.swapaxes(w3, 1, 2), tile_expert)
    )
    dw1 = _drhs(lhs, dgate_pre, tile_expert, e)
    dw3 = _drhs(lhs, dup_pre, tile_expert, e)
    ds1 = _tile_segsum(g_raw.astype(jnp.float32) * dgate, tile_expert, e)
    ds3 = _tile_segsum(u_raw.astype(jnp.float32) * dup, tile_expert, e)
    dte = np.zeros(tile_expert.shape, jax.dtypes.float0)
    return (dlhs.astype(lhs.dtype), dw1.astype(w1.dtype),
            dw3.astype(w3.dtype), dte,
            ds1.astype(scale1.dtype), ds3.astype(scale3.dtype))


_gmm_swiglu_vjp.defvjp(_gmm_swiglu_fwd, _gmm_swiglu_bwd)


def gmm_swiglu(lhs, w1, w3, tile_expert, scale1, scale3, *,
               row_tile: int = TILE_M):
    """Fused grouped SwiGLU front half:

        out[i] = silu(lhs[i] @ w1[e] * s1[e]) * (lhs[i] @ w3[e] * s3[e])

    with e = tile_expert[i]. One kernel launch computes both grouped
    matmuls into f32 accumulators and applies scale + silu + multiply
    in the epilogue — vs the unfused path's two launches plus two
    [M, ffn] HBM round-trips for the separate gate/up tensors. scale1/
    scale3 are [E, N]; pass ones for unquantized weights (the f32
    multiply by 1.0 is exact). The caller's w2 projection stays a
    separate gmm/gmm_scaled (different contraction dim)."""
    _check_row_tile(lhs.shape[0], tile_expert, row_tile, "gmm_swiglu")
    return _gmm_swiglu_vjp(lhs, w1, w3, tile_expert, scale1, scale3)
