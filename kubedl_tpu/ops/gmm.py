"""Grouped matrix multiply — the dropless-MoE expert FFN kernel.

`gmm(lhs, rhs, tile_expert)` computes, for every row-tile i of `lhs`,
`lhs[i] @ rhs[tile_expert[i]]` — i.e. a matmul whose weight matrix
changes per row-group. This is the TPU-native alternative to both of
the classic MoE dispatch shapes:

  * GShard's dense one-hot einsums burn S*E*C*d FLOPs per dispatch —
    measured equal to the expert FFN compute itself (models/moe.py);
  * capacity-slot gather/scatter (models/moe.py today) is
    bandwidth-cheap but still RUNS the expert matmuls over every
    capacity slot: at capacity_factor 1.25 that is a hard 1/1.25
    ceiling on MFU (the committed 0.474 at dense 0.60 is exactly that
    ceiling).

Here tokens are sorted by expert and padded per group to the row-tile
size, so the expert matmuls touch `top_k*S + E*tile_m` rows — a few
percent of tile rounding instead of 25% capacity padding, and NO
dropped tokens.

Mechanics (ref: the megablox `gmm` pattern from public JAX —
SNIPPETS.md has no counterpart; built from the pallas guide):
  * caller guarantees every row-tile belongs to exactly ONE group and
    passes `tile_expert[num_m_tiles]`; the scalar-prefetch grid spec
    lets the rhs BlockSpec index_map select the expert's weight block
    per tile before the kernel body runs;
  * grid (m_tiles, n_tiles, k_tiles), k innermost sequential; f32
    accumulator scratch, cast on the last k step;
  * backward: dlhs is the same gmm against rhs^T (per expert);
    drhs is `tgmm` — grid (k, n, m) with m innermost sequential,
    accumulating row-tiles into the owning expert's [K, N] block
    (zeroed on the group's first tile).

Like ops/flash_attention.py, kernels run in interpret mode off-TPU so
CPU tests exercise the real kernel logic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_M = 128
_TILE_N = 256
_TILE_K = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _check_tiled(m: int, tile_expert, name: str) -> None:
    if m % TILE_M:
        raise ValueError(
            f"{name} lhs rows ({m}) must be a multiple of TILE_M ({TILE_M}); "
            "the grid covers m // TILE_M tiles and a ragged tail would "
            "silently never be computed")
    if tile_expert.shape[0] != m // TILE_M:
        raise ValueError(
            f"{name} tile_expert has {tile_expert.shape[0]} entries for "
            f"{m // TILE_M} row-tiles; an out-of-range te[i] gather clamps "
            "and would silently reuse the last expert's weights")


def _pick(dim: int, pref: int) -> int:
    """Largest tile <= pref that divides dim (dims here are model sizes —
    multiples of 128 in practice; fall back to the dim itself)."""
    for t in (pref, 512, 256, 128):
        if t <= pref and dim % t == 0:
            return t
    return dim


# -- forward -----------------------------------------------------------------


def _gmm_kernel(te_ref, lhs_ref, rhs_ref, out_ref, acc_ref, *, nk):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(
        lhs_ref[...], rhs_ref[0],
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == nk - 1)
    def _flush():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _gmm_raw(lhs, rhs, tile_expert):
    m, k = lhs.shape
    _, _, n = rhs.shape
    _check_tiled(m, tile_expert, "gmm")
    tm = TILE_M
    tk = _pick(k, _TILE_K)
    tn = _pick(n, _TILE_N)
    nk = k // tk
    grid = (m // tm, n // tn, nk)
    return pl.pallas_call(
        functools.partial(_gmm_kernel, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda i, j, kk, te: (i, kk)),
                pl.BlockSpec((1, tk, tn), lambda i, j, kk, te: (te[i], kk, j)),
            ],
            out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk, te: (i, j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n, bytes_accessed=0, transcendentals=0),
        interpret=_interpret(),
    )(tile_expert, lhs, rhs)


# -- transposed (weight-gradient) --------------------------------------------


def _tgmm_kernel(te_ref, first_ref, lhs_ref, dout_ref, out_ref):
    mm = pl.program_id(2)

    @pl.when(first_ref[mm] == 1)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += jnp.dot(
        lhs_ref[...].T, dout_ref[...],
        preferred_element_type=jnp.float32,
    )[None]


def _tgmm_raw(lhs, dout, tile_expert, first_tile, n_experts):
    """drhs[e] = sum over e's row-tiles of lhs_tile^T @ dout_tile.
    Experts with no tiles keep whatever was in their block — callers
    mask them to zero (cheap jnp.where on group counts)."""
    m, k = lhs.shape
    _, n = dout.shape
    _check_tiled(m, tile_expert, "tgmm")
    tm = TILE_M
    tk = _pick(k, _TILE_K)
    tn = _pick(n, _TILE_N)
    grid = (k // tk, n // tn, m // tm)
    return pl.pallas_call(
        _tgmm_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda kk, j, i, te, fi: (i, kk)),
                pl.BlockSpec((tm, tn), lambda kk, j, i, te, fi: (i, j)),
            ],
            out_specs=pl.BlockSpec(
                (1, tk, tn), lambda kk, j, i, te, fi: (te[i], kk, j)),
            scratch_shapes=[],
        ),
        out_shape=jax.ShapeDtypeStruct((n_experts, k, n), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n, bytes_accessed=0, transcendentals=0),
        interpret=_interpret(),
    )(tile_expert, first_tile, lhs, dout)


# -- public op with VJP ------------------------------------------------------


@jax.custom_vjp
def gmm(lhs, rhs, tile_expert):
    """[M, K] x [E, K, N] -> [M, N], weight chosen per row-tile.

    `tile_expert[i]` names the expert for row-tile i (rows sorted and
    per-group padded to TILE_M by the caller — see moe.py's dropless
    dispatch). Padding rows are zeros; they multiply into zeros and are
    never gathered back.
    """
    return _gmm_raw(lhs, rhs, tile_expert)


def _gmm_fwd(lhs, rhs, tile_expert):
    return _gmm_raw(lhs, rhs, tile_expert), (lhs, rhs, tile_expert)


def _gmm_bwd(res, dout):
    lhs, rhs, tile_expert = res
    dlhs = _gmm_raw(dout, jnp.swapaxes(rhs, 1, 2), tile_expert)
    first = _first_tile_flags(tile_expert)
    drhs = _tgmm_raw(lhs, dout, tile_expert, first, rhs.shape[0])
    # experts that own no tiles were never written — mask their garbage
    owned = jnp.zeros((rhs.shape[0],), jnp.int32).at[tile_expert].add(
        1, mode="drop")
    drhs = jnp.where((owned > 0)[:, None, None], drhs, 0.0)
    dte = np.zeros(tile_expert.shape, jax.dtypes.float0)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), dte


gmm.defvjp(_gmm_fwd, _gmm_bwd)


def _first_tile_flags(tile_expert):
    """1 where a tile starts a new expert run (m-order), else 0."""
    prev = jnp.concatenate(
        [jnp.full((1,), -1, tile_expert.dtype), tile_expert[:-1]])
    return (tile_expert != prev).astype(jnp.int32)
