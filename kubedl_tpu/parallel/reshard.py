"""Reshard planner — Tenplex-style tensor-collection slicing between meshes.

Elastic resize and dead-slice shrink change the device mesh under a live
training state. Instead of the checkpoint round trip (Orbax save -> pod
recreate -> restore: minutes of lost capacity per event), the state can be
resharded: every parameter / optimizer-slot leaf is a tensor collection cut
into per-device chunks by its PartitionSpec, and the old and new chunkings
overlap in computable hyperrectangle intersections. This module computes
those intersections and emits a minimal pod-to-pod transfer plan:

  * blocks already resident on their destination pod are "local" (zero DCN
    bytes — the common case for a shrink that keeps survivors in place);
  * replicated blocks are fetched from exactly ONE source (lowest surviving
    pod id), never broadcast;
  * a block no surviving pod holds raises PlanError — the caller falls back
    closed to checkpoint restore (train/reshard_runtime.py ladder).

The planner is pure (shapes + specs + mesh axes in, transfers out) so the
trainer, the scheduler and the property tests (tests/test_reshard.py) all
agree on one plan; `ReshardPlan.digest()` is the cross-pod consistency
check — pods compute the plan independently and any digest mismatch aborts
the reshard before a byte moves.
"""
from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from kubedl_tpu.parallel.mesh import AXIS_ORDER

# A hyperrectangle in GLOBAL leaf coordinates: ((start, stop), ...) per dim.
Rect = Tuple[Tuple[int, int], ...]


class PlanError(ValueError):
    """The (old, new) pair cannot be live-resharded (non-divisible shapes,
    or a needed block lives only on dead pods). Callers fall back closed."""


@dataclass(frozen=True)
class Transfer:
    """One block move: `rect` (global coords) from pod `src` to pod `dst`."""

    path: str
    src: int
    dst: int
    rect: Rect
    nbytes: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(b - a for a, b in self.rect)


@dataclass
class ReshardPlan:
    old_axes: Dict[str, int]
    new_axes: Dict[str, int]
    old_pods: int
    new_pods: int
    # blocks that must cross pods (the DCN traffic)
    transfers: List[Transfer] = field(default_factory=list)
    # blocks whose chosen source pod IS the destination pod (no movement
    # for an in-memory reshard; the staged-restart lane persists them too,
    # since nothing survives a process exit)
    locals_: List[Transfer] = field(default_factory=list)

    @property
    def moved_bytes(self) -> int:
        return sum(t.nbytes for t in self.transfers)

    @property
    def local_bytes(self) -> int:
        return sum(t.nbytes for t in self.locals_)

    @property
    def total_bytes(self) -> int:
        return self.moved_bytes + self.local_bytes

    def for_source(self, pod: int) -> List[Transfer]:
        """Every block pod `pod` must ship (staged lane: including blocks
        it keeps for itself — a restarted process has no live memory)."""
        return [t for t in self.transfers if t.src == pod] + [
            t for t in self.locals_ if t.src == pod
        ]

    def for_dest(self, pod: int) -> List[Transfer]:
        return [t for t in self.transfers if t.dst == pod] + [
            t for t in self.locals_ if t.dst == pod
        ]

    def digest(self) -> str:
        """Topology+plan fingerprint. Pods compute the plan independently
        from their own view of (old, new); equal digests prove they will
        stage/expect the same blocks — a mismatch aborts the reshard."""
        canon = {
            "old_axes": {k: self.old_axes.get(k, 1) for k in AXIS_ORDER},
            "new_axes": {k: self.new_axes.get(k, 1) for k in AXIS_ORDER},
            "old_pods": self.old_pods,
            "new_pods": self.new_pods,
            "moves": sorted(
                (t.path, t.src, t.dst, t.rect, t.nbytes)
                for t in self.transfers + self.locals_
            ),
        }
        blob = json.dumps(canon, sort_keys=True, default=list)
        return hashlib.sha256(blob.encode()).hexdigest()


# ---------------------------------------------------------------------------
# mesh / spec geometry
# ---------------------------------------------------------------------------


def mesh_device_count(axes: Dict[str, int]) -> int:
    return math.prod(int(axes.get(name, 1)) for name in AXIS_ORDER)


def normalize_spec(spec, ndim: int) -> List[Tuple[str, ...]]:
    """PartitionSpec -> per-dim tuple of mesh axis names (padded to ndim)."""
    entries: List[Tuple[str, ...]] = []
    for part in tuple(spec or ()):
        if part is None:
            entries.append(())
        elif isinstance(part, str):
            entries.append((part,))
        else:
            entries.append(tuple(part))
    while len(entries) < ndim:
        entries.append(())
    return entries[:ndim]


def _chunk_counts(
    shape: Sequence[int], dims: List[Tuple[str, ...]], axes: Dict[str, int]
) -> List[int]:
    counts = []
    for size, names in zip(shape, dims):
        n = math.prod(int(axes.get(a, 1)) for a in names)
        if n > 1 and size % n:
            raise PlanError(
                f"dim of size {size} not divisible by {n} shards "
                f"(axes {names}, mesh {dict(axes)})"
            )
        counts.append(n)
    return counts


def _device_chunk_vecs(
    shape: Sequence[int], dims: List[Tuple[str, ...]], axes: Dict[str, int]
) -> List[Tuple[int, ...]]:
    """Per mesh device (flat AXIS_ORDER index): its chunk-index vector for
    a leaf — which chunk of each dim the device owns. Devices differing
    only on unsharded axes share a vector (replication)."""
    sizes = [int(axes.get(name, 1)) for name in AXIS_ORDER]
    pos = {name: i for i, name in enumerate(AXIS_ORDER)}
    vecs = []
    for flat in range(math.prod(sizes)):
        coords = np.unravel_index(flat, sizes)
        vec = []
        for names in dims:
            idx = 0
            for a in names:
                idx = idx * sizes[pos[a]] + int(coords[pos[a]])
            vec.append(idx)
        vecs.append(tuple(vec))
    return vecs


def pod_of_device(flat: int, n_devices: int, n_pods: int) -> int:
    """Mesh devices partition into pods by contiguous flat index —
    jax.devices() orders by process, and build_mesh reshapes that order."""
    if n_devices % n_pods:
        raise PlanError(f"{n_devices} devices not divisible by {n_pods} pods")
    return flat // (n_devices // n_pods)


def _owner_map(
    shape: Sequence[int],
    dims: List[Tuple[str, ...]],
    axes: Dict[str, int],
    n_pods: int,
) -> Dict[Tuple[int, ...], List[int]]:
    """chunk vector -> sorted pod ids holding (a replica of) that chunk."""
    n_dev = mesh_device_count(axes)
    owners: Dict[Tuple[int, ...], set] = {}
    for flat, vec in enumerate(_device_chunk_vecs(shape, dims, axes)):
        owners.setdefault(vec, set()).add(pod_of_device(flat, n_dev, n_pods))
    return {vec: sorted(pods) for vec, pods in owners.items()}


def _dim_intervals(size: int, n_old: int, n_new: int):
    """Elementary intervals of one dim under both chunkings: each interval
    lies inside exactly one old chunk and one new chunk. Yields
    (start, stop, old_chunk_idx, new_chunk_idx)."""
    old_len, new_len = size // n_old, size // n_new
    cuts = sorted({0, size}
                  | {i * old_len for i in range(n_old)}
                  | {i * new_len for i in range(n_new)})
    for a, b in zip(cuts, cuts[1:]):
        yield a, b, a // old_len, a // new_len


def chunk_rect(
    shape: Sequence[int], counts: Sequence[int], vec: Sequence[int]
) -> Rect:
    """Global hyperrect of one chunk vector."""
    out = []
    for size, n, idx in zip(shape, counts, vec):
        ln = size // n
        out.append((idx * ln, (idx + 1) * ln))
    return tuple(out)


# ---------------------------------------------------------------------------
# per-leaf planning
# ---------------------------------------------------------------------------


def plan_leaf(
    path: str,
    shape: Sequence[int],
    itemsize: int,
    spec,
    old_axes: Dict[str, int],
    new_axes: Dict[str, int],
    old_pods: int = 1,
    new_pods: int = 1,
    survivors: Optional[Iterable[int]] = None,
) -> Tuple[List[Transfer], List[Transfer]]:
    """(cross-pod transfers, local blocks) for one leaf.

    `survivors` restricts eligible SOURCE pods (dead-slice shrink: the dead
    pod's blocks must come from replicas elsewhere); None = all old pods.
    Every destination pod receives each block it needs exactly once.
    """
    shape = tuple(int(s) for s in shape)
    ndim = len(shape)
    old_dims = normalize_spec(spec, ndim)
    new_dims = old_dims  # the SPEC is mesh-shape-agnostic; only sizes change
    old_counts = _chunk_counts(shape, old_dims, old_axes)
    new_counts = _chunk_counts(shape, new_dims, new_axes)
    src_owners = _owner_map(shape, old_dims, old_axes, old_pods)
    dst_owners = _owner_map(shape, new_dims, new_axes, new_pods)
    alive = set(range(old_pods)) if survivors is None else set(survivors)

    # elementary interval lists per dim
    per_dim = [
        list(_dim_intervals(s, no, nn))
        for s, no, nn in zip(shape, old_counts, new_counts)
    ]
    # scalars (0-dim leaves: optimizer step counts) still reshard: one
    # empty-rect block, old vec == new vec == ()
    if ndim == 0:
        per_dim = []

    transfers: List[Transfer] = []
    locals_: List[Transfer] = []

    def emit(rect: Rect, old_vec, new_vec) -> None:
        nbytes = itemsize * math.prod(b - a for a, b in rect)
        srcs = [p for p in src_owners.get(tuple(old_vec), []) if p in alive]
        if not srcs:
            raise PlanError(
                f"{path}: block {rect} has no surviving source pod "
                f"(owners {src_owners.get(tuple(old_vec))}, alive {sorted(alive)})"
            )
        for dst in dst_owners.get(tuple(new_vec), []):
            src = dst if dst in srcs else srcs[0]
            t = Transfer(path=path, src=src, dst=dst, rect=rect, nbytes=nbytes)
            (locals_ if src == dst else transfers).append(t)

    if ndim == 0:
        emit((), (), ())
        return transfers, locals_

    def rec(d: int, rect: List[Tuple[int, int]], ov: List[int], nv: List[int]):
        if d == ndim:
            emit(tuple(rect), tuple(ov), tuple(nv))
            return
        for a, b, oi, ni in per_dim[d]:
            rec(d + 1, rect + [(a, b)], ov + [oi], nv + [ni])

    rec(0, [], [], [])
    return transfers, locals_


def plan_reshard(
    leaves: Dict[str, Tuple[Tuple[int, ...], int, object]],
    old_axes: Dict[str, int],
    new_axes: Dict[str, int],
    old_pods: int = 1,
    new_pods: int = 1,
    survivors: Optional[Iterable[int]] = None,
) -> ReshardPlan:
    """Plan a whole state: `leaves` maps path -> (shape, itemsize, spec).

    Optimizer slots reshard WITH their params by construction: a slot leaf
    carries its param's shape and PartitionSpec, so its blocks are cut and
    routed identically (pinned by tests/test_reshard.py).
    """
    plan = ReshardPlan(
        old_axes=dict(old_axes), new_axes=dict(new_axes),
        old_pods=old_pods, new_pods=new_pods,
    )
    for path in sorted(leaves):
        shape, itemsize, spec = leaves[path]
        t, l = plan_leaf(
            path, shape, itemsize, spec, old_axes, new_axes,
            old_pods=old_pods, new_pods=new_pods, survivors=survivors,
        )
        plan.transfers.extend(t)
        plan.locals_.extend(l)
    return plan


def leaves_from_state(state) -> Dict[str, Tuple[Tuple[int, ...], int, object]]:
    """Extract (shape, itemsize, PartitionSpec) per leaf from a LIVE sharded
    pytree (params, optimizer state, step — everything reshards together).
    Requires NamedSharding on every leaf; anything else means the state's
    layout is not expressible as a spec and the caller must fall back."""
    import jax
    from jax.sharding import NamedSharding

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    out = {}
    for keypath, leaf in flat:
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            raise PlanError(
                f"leaf {jax.tree_util.keystr(keypath)} has "
                f"{type(sharding).__name__}, not NamedSharding — layout "
                f"unknown, cannot plan a reshard"
            )
        out[jax.tree_util.keystr(keypath)] = (
            tuple(leaf.shape), leaf.dtype.itemsize, sharding.spec
        )
    return out


# ---------------------------------------------------------------------------
# numpy reference executor (property tests + the staged-restart lane)
# ---------------------------------------------------------------------------


def extract_block(arr: np.ndarray, rect: Rect) -> np.ndarray:
    return arr[tuple(slice(a, b) for a, b in rect)]


def pod_region(
    shape: Sequence[int], spec, axes: Dict[str, int], n_pods: int, pod: int
) -> List[Rect]:
    """Deduped chunk hyperrects pod `pod` owns for a leaf under a mesh."""
    shape = tuple(int(s) for s in shape)
    dims = normalize_spec(spec, len(shape))
    counts = _chunk_counts(shape, dims, axes)
    n_dev = mesh_device_count(axes)
    rects = []
    seen = set()
    for flat, vec in enumerate(_device_chunk_vecs(shape, dims, axes)):
        if pod_of_device(flat, n_dev, n_pods) != pod or vec in seen:
            continue
        seen.add(vec)
        rects.append(chunk_rect(shape, counts, vec))
    return rects


def assemble(
    shape: Sequence[int],
    dtype,
    pieces: Iterable[Tuple[Rect, np.ndarray]],
    region: Optional[Rect] = None,
) -> np.ndarray:
    """Build `region` (default: the whole leaf) from blocks, verifying
    exactly-once coverage — partial or overlapping delivery raises
    PlanError instead of returning silently corrupt state."""
    shape = tuple(int(s) for s in shape)
    if region is None:
        region = tuple((0, s) for s in shape)
    off = [a for a, _ in region]
    rshape = tuple(b - a for a, b in region)
    out = np.zeros(rshape, dtype=dtype)
    count = np.zeros(rshape, dtype=np.int16)
    for rect, block in pieces:
        sl = tuple(
            slice(a - o, b - o) for (a, b), o in zip(rect, off)
        )
        if block.shape != tuple(b - a for a, b in rect):
            raise PlanError(f"block shape {block.shape} != rect {rect}")
        out[sl] = block
        count[sl] += 1
    if (count != 1).any():
        under = int((count == 0).sum())
        over = int((count > 1).sum())
        raise PlanError(
            f"coverage violation assembling {region}: {under} elements "
            f"missing, {over} delivered more than once"
        )
    return out
