"""Pipeline parallelism — GPipe schedule over the mesh's "stage" axis.

The reference has no pipeline parallelism anywhere (SURVEY.md §2.4: "Pipeline
parallelism (PP): absent"); this is the net-new TPU-native implementation the
JAXJob mesh spec promises. Design is the canonical TPU pipelining recipe, not
a send/recv translation:

  * layers are stacked on a leading dim and sharded over the "stage" mesh
    axis, so each stage holds `n_layers / n_stages` layers;
  * a single `shard_map` runs the classic GPipe loop: at step i, stage 0
    ingests microbatch i, every stage applies its local layers (a
    `lax.scan` over the stacked leaf dim), and activations rotate to the
    next stage with one `ppermute` — a nearest-neighbor ICI hop, the
    cheapest collective on a TPU torus;
  * the loop itself is a `lax.scan` over `n_microbatches + n_stages - 1`
    steps — static control flow, one compiled program, no per-step
    dispatch;
  * autodiff flows through scan+ppermute, so `jax.grad` of a pipelined
    loss is the pipelined backward pass for free.

Composes with data parallelism (batch sharded over data+fsdp, params
replicated across those axes inside the stage shard_map) and with MoE
layers (experts replicated per stage, aux loss threaded through the
schedule — models/llama.py forward_pipelined_and_aux). Tensor/context/
expert MESH AXES inside a pipelined layer would need manual collectives
in shard_map and stay out of scope for the pipelined path — use
tp/cp/ep on the non-pipelined forward instead.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kubedl_tpu.api.validation import validate_pipeline_shapes
from kubedl_tpu.utils.jax_compat import shard_map

from kubedl_tpu.parallel.mesh import BATCH_AXES


def schedule_steps(n_micro: int, n_stages: int, interleave: int = 1) -> int:
    """Sequential sub-steps one schedule round takes. GPipe (interleave=1)
    runs M + S - 1 full-stage steps; the interleaved circular schedule
    runs M*v + S - 1 steps of 1/v the per-step work."""
    return n_micro * interleave + n_stages - 1


def bubble_fraction(n_micro: int, n_stages: int, interleave: int = 1) -> float:
    """Fill/drain bubble fraction of the schedule: (S-1)/(M*v + S-1).

    Each rank does M*v useful chunk-steps out of M*v + S - 1 total — the
    interleave-v schedule keeps the same S-1 idle chunk-steps but each
    chunk-step is 1/v the work, so the wasted FRACTION shrinks by ~1/v
    (the MPMD pipeline-parallelism paper's first-order bubble model)."""
    return (n_stages - 1) / schedule_steps(n_micro, n_stages, interleave)


def interleaved_layer_order(
    n_layers: int, n_stages: int, interleave: int
) -> np.ndarray:
    """Layer permutation for the interleaved schedule's stacked layout.

    The stacked-params leading dim is sharded contiguously over "stage"
    (rank s holds block [s*L/S, (s+1)*L/S)), but the interleaved schedule
    assigns rank s the NON-contiguous chunks {r*S + s : r < v} (each
    chunk is L/(S*v) layers). This permutation reorders natural layer
    order so each rank's contiguous block holds exactly its v chunks, in
    local chunk order — gather stacked leaves with it before shard_map.
    """
    chunk_len = n_layers // (n_stages * interleave)
    order = []
    for s in range(n_stages):
        for r in range(interleave):
            c = r * n_stages + s
            order.extend(range(c * chunk_len, (c + 1) * chunk_len))
    return np.asarray(order, dtype=np.int32)


def stack_layers(layers: Sequence[Any]) -> Any:
    """[{leaf...}] * L  ->  {leaf: [L, ...]} — the stacked-params layout the
    pipeline (and `lax.scan` over layers generally) wants."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked: Any, n_layers: int) -> list:
    return [
        jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n_layers)
    ]


def pipeline_apply(
    stacked_params: Any,
    x_microbatches: jax.Array,  # [n_micro, micro_batch, ...feature dims]
    layer_fn: Callable[[jax.Array, Any], jax.Array],
    *,
    mesh: Mesh,
    stage_axis: str = "stage",
    batch_axes: Tuple[str, ...] = BATCH_AXES,
    remat: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run every microbatch through all pipeline stages; returns
    (activations shaped like `x_microbatches`, aux_total scalar).

    `stacked_params` leaves have leading dim n_layers (divisible by the
    stage-axis size); `layer_fn(act, layer_params) -> (act, aux_scalar)`
    applies ONE layer, must be shape-preserving, and reports a per-layer
    aux scalar — e.g. the MoE load-balance loss (dense layers return a
    zero scalar). Microbatch dim 0 is the pipeline's time axis; dim 1
    (micro batch) is sharded over `batch_axes`.

    Aux contributions are gated to each stage's VALID window (the GPipe
    fill/drain steps feed clipped garbage that must not count), summed
    over this stage's layers and steps, psummed across stages, and
    averaged over microbatches — the microbatch-mean approximation of
    the full-batch aux every per-shard MoE implementation uses.
    """
    n_stages = mesh.shape[stage_axis]
    n_micro = x_microbatches.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"need >= {n_stages} microbatches to fill a {n_stages}-stage "
            f"pipeline, got {n_micro}"
        )
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(
            f"stacked layer count {n_layers} not divisible by the "
            f"{stage_axis}-axis size {n_stages}"
        )
    x_rank = x_microbatches.ndim

    per_layer = layer_fn
    if remat:
        per_layer = jax.checkpoint(per_layer)

    def run_local_layers(act, params_local):
        def body(carry, layer):
            a, aux = carry
            a, da = per_layer(a, layer)
            return (a, aux + da), None

        (act, aux), _ = jax.lax.scan(
            body, (act, jnp.zeros((), jnp.float32)), params_local)
        return act, aux

    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_steps = n_micro + n_stages - 1

    def pipelined(params_local, x_mub):
        stage = jax.lax.axis_index(stage_axis)
        out_buf = jnp.zeros_like(x_mub)
        act = jnp.zeros_like(x_mub[0])

        def step(carry, i):
            act, out_buf, aux_acc = carry
            # stage 0 ingests microbatch i (clipped: trailing drain steps
            # feed garbage that never reaches an output slot)
            inp = jax.lax.dynamic_index_in_dim(
                x_mub, jnp.clip(i, 0, n_micro - 1), 0, keepdims=False
            )
            act = jnp.where(stage == 0, inp, act)
            act, aux = run_local_layers(act, params_local)
            # stage s does REAL work on microbatch i-s; fill/drain steps
            # process clipped garbage whose aux must not count
            valid = jnp.logical_and(i - stage >= 0, i - stage < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # last stage banks finished microbatch i-(n_stages-1)
            out_idx = jnp.clip(i - (n_stages - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, out_idx, 0, keepdims=False)
            bank = jnp.where(
                jnp.logical_and(stage == n_stages - 1, i >= n_stages - 1), act, cur
            )
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, bank, out_idx, 0)
            # rotate activations one ICI hop to the next stage
            act = jax.lax.ppermute(act, stage_axis, perm)
            return (act, out_buf, aux_acc), None

        (act, out_buf, aux_acc), _ = jax.lax.scan(
            step, (act, out_buf, jnp.zeros((), jnp.float32)),
            jnp.arange(n_steps, dtype=jnp.int32)
        )
        # every stage contributes its own layers' aux; mean over
        # microbatches approximates the full-batch value, pmean over the
        # batch axes makes it a true global (replicated) scalar
        aux_total = jax.lax.psum(aux_acc, stage_axis) / n_micro
        aux_total = jax.lax.pmean(aux_total, batch_axes)
        # leading singleton picks out this stage's copy; only the last
        # stage's buffer holds real outputs and the caller slices it.
        return out_buf[None], aux_total

    params_spec = jax.tree_util.tree_map(lambda _: P(stage_axis), stacked_params)
    x_spec = P(None, batch_axes, *([None] * (x_rank - 2)))
    out_spec = P(stage_axis, None, batch_axes, *([None] * (x_rank - 2)))

    out, aux = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=(out_spec, P()),
    )(stacked_params, x_microbatches)
    return out[-1], aux


def pipeline_apply_1f1b(
    stacked_params: Any,
    x_microbatches: jax.Array,  # [n_micro, micro_batch, ...feature dims]
    layer_fn: Callable[[jax.Array, Any], jax.Array],
    *,
    mesh: Mesh,
    interleave: int = 1,
    stage_axis: str = "stage",
    batch_axes: Tuple[str, ...] = BATCH_AXES,
    remat: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Interleaved circular 1F1B schedule (virtual pipeline stages).

    Same contract as `pipeline_apply` (which stays the GPipe parity
    oracle), but each rank holds `interleave` NON-contiguous layer chunks
    and every microbatch circulates the ring `interleave` times: rank s,
    repeat r applies global chunk r*S + s at work index u = r*M + i, step
    t = u + s. An activation leaving the last rank at repeat r < v-1
    wraps to rank 0 (through a per-rank wrap buffer: the ring ppermute
    delivers it S steps after it was computed, and rank 0 holds it until
    step (r+1)*M + i — which requires M >= S, the same fill constraint
    GPipe has). The loop is one `lax.scan` over M*v + S - 1 sub-steps,
    each costing 1/v of a GPipe step — the fill/drain bubble FRACTION
    drops from (S-1)/(M+S-1) to (S-1)/(M*v+S-1), ~1/v (bubble_fraction).

    `interleave=1` degenerates to the GPipe schedule on a different code
    path (wrap buffer never used) — the parity tests pin all three ways.
    Autodiff through scan+ppermute+gather gives the pipelined backward;
    the steady-state one-forward-one-backward alternation of true 1F1B
    is realized in the MPMD runtime (train/pipeline_runtime.py), where
    forward and backward are separate per-microbatch programs.
    """
    n_stages = mesh.shape[stage_axis]
    n_micro = x_microbatches.shape[0]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    errs = validate_pipeline_shapes(
        n_stages, n_micro, interleave, n_layers=n_layers,
        path="pipeline_apply_1f1b")
    if errs:
        raise ValueError("; ".join(errs))
    v = interleave
    chunk_len = n_layers // (n_stages * v)
    x_rank = x_microbatches.ndim

    per_layer = layer_fn
    if remat:
        per_layer = jax.checkpoint(per_layer)

    def run_chunk(act, chunk_params):
        def body(carry, layer):
            a, aux = carry
            a, da = per_layer(a, layer)
            return (a, aux + da), None

        (act, aux), _ = jax.lax.scan(
            body, (act, jnp.zeros((), jnp.float32)), chunk_params)
        return act, aux

    # reorder layers so each rank's contiguous stacked block holds its v
    # chunks (differentiable gather: grads scatter back to natural order)
    order = jnp.asarray(interleaved_layer_order(n_layers, n_stages, v))
    permuted = jax.tree_util.tree_map(lambda p: p[order], stacked_params)

    ring = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_work = n_micro * v
    n_steps = n_work + n_stages - 1

    def pipelined(params_local, x_mub):
        stage = jax.lax.axis_index(stage_axis)
        out_buf = jnp.zeros_like(x_mub)
        wrap_buf = jnp.zeros_like(x_mub)
        act = jnp.zeros_like(x_mub[0])
        # local block [v*chunk_len, ...] -> [v, chunk_len, ...] for the
        # traced repeat-index gather
        chunks = jax.tree_util.tree_map(
            lambda p: p.reshape((v, chunk_len) + p.shape[1:]), params_local)

        def step(carry, t):
            act, out_buf, wrap_buf, aux_acc = carry
            u = t - stage  # this rank's work index at step t
            valid = jnp.logical_and(u >= 0, u < n_work)
            uc = jnp.clip(u, 0, n_work - 1)
            r, mb = uc // n_micro, uc % n_micro
            # -- rank 0: bank the wrapped activation that just arrived.
            # The carried `act` was sent by rank S-1 at step t-1, work
            # index t - S; repeats below v-1 recirculate (the final
            # repeat's output banks into out_buf instead).
            us = jnp.clip(t - n_stages, 0, n_work - 1)
            r_s, mb_s = us // n_micro, us % n_micro
            wrap_store = jnp.logical_and(
                jnp.logical_and(stage == 0, r_s < v - 1),
                jnp.logical_and(t - n_stages >= 0, t - n_stages < n_work))
            cur_wrap = jax.lax.dynamic_index_in_dim(
                wrap_buf, mb_s, 0, keepdims=False)
            wrap_buf = jax.lax.dynamic_update_index_in_dim(
                wrap_buf, jnp.where(wrap_store, act, cur_wrap), mb_s, 0)
            # -- rank 0 input: fresh microbatch on repeat 0, the wrap
            # buffer afterwards (store-before-read covers M == S, where
            # the wrap arrives exactly when it is needed)
            fresh = jax.lax.dynamic_index_in_dim(x_mub, mb, 0, keepdims=False)
            wrapped = jax.lax.dynamic_index_in_dim(
                wrap_buf, mb, 0, keepdims=False)
            act = jnp.where(stage == 0, jnp.where(r == 0, fresh, wrapped), act)
            # -- apply this rank's repeat-r chunk
            chunk = jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(p, r, 0, keepdims=False),
                chunks)
            act, aux = run_chunk(act, chunk)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # -- last rank, final repeat: bank finished microbatch mb
            bank = jnp.logical_and(
                jnp.logical_and(stage == n_stages - 1, valid), r == v - 1)
            cur_out = jax.lax.dynamic_index_in_dim(out_buf, mb, 0, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(bank, act, cur_out), mb, 0)
            # -- rotate one ICI hop (S-1 -> 0 carries the wrap)
            act = jax.lax.ppermute(act, stage_axis, ring)
            return (act, out_buf, wrap_buf, aux_acc), None

        (act, out_buf, wrap_buf, aux_acc), _ = jax.lax.scan(
            step, (act, out_buf, wrap_buf, jnp.zeros((), jnp.float32)),
            jnp.arange(n_steps, dtype=jnp.int32)
        )
        # every layer contributes once per microbatch, same normalization
        # as the GPipe oracle: psum stage contributions, mean over
        # microbatches, pmean to a replicated scalar over batch axes
        aux_total = jax.lax.psum(aux_acc, stage_axis) / n_micro
        aux_total = jax.lax.pmean(aux_total, batch_axes)
        return out_buf[None], aux_total

    params_spec = jax.tree_util.tree_map(lambda _: P(stage_axis), permuted)
    x_spec = P(None, batch_axes, *([None] * (x_rank - 2)))
    out_spec = P(stage_axis, None, batch_axes, *([None] * (x_rank - 2)))

    out, aux = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=(out_spec, P()),
    )(permuted, x_microbatches)
    return out[-1], aux


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [n_micro, B/n_micro, ...]."""
    if x.shape[0] % n_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_microbatches} microbatches"
        )
    return x.reshape((n_microbatches, x.shape[0] // n_microbatches) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[n_micro, mb, ...] -> [n_micro*mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
