"""Pipeline parallelism — GPipe schedule over the mesh's "stage" axis.

The reference has no pipeline parallelism anywhere (SURVEY.md §2.4: "Pipeline
parallelism (PP): absent"); this is the net-new TPU-native implementation the
JAXJob mesh spec promises. Design is the canonical TPU pipelining recipe, not
a send/recv translation:

  * layers are stacked on a leading dim and sharded over the "stage" mesh
    axis, so each stage holds `n_layers / n_stages` layers;
  * a single `shard_map` runs the classic GPipe loop: at step i, stage 0
    ingests microbatch i, every stage applies its local layers (a
    `lax.scan` over the stacked leaf dim), and activations rotate to the
    next stage with one `ppermute` — a nearest-neighbor ICI hop, the
    cheapest collective on a TPU torus;
  * the loop itself is a `lax.scan` over `n_microbatches + n_stages - 1`
    steps — static control flow, one compiled program, no per-step
    dispatch;
  * autodiff flows through scan+ppermute, so `jax.grad` of a pipelined
    loss is the pipelined backward pass for free.

Composes with data parallelism (batch sharded over data+fsdp, params
replicated across those axes inside the stage shard_map) and with MoE
layers (experts replicated per stage, aux loss threaded through the
schedule — models/llama.py forward_pipelined_and_aux). Tensor/context/
expert MESH AXES inside a pipelined layer would need manual collectives
in shard_map and stay out of scope for the pipelined path — use
tp/cp/ep on the non-pipelined forward instead.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubedl_tpu.utils.jax_compat import shard_map

from kubedl_tpu.parallel.mesh import BATCH_AXES


def stack_layers(layers: Sequence[Any]) -> Any:
    """[{leaf...}] * L  ->  {leaf: [L, ...]} — the stacked-params layout the
    pipeline (and `lax.scan` over layers generally) wants."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked: Any, n_layers: int) -> list:
    return [
        jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n_layers)
    ]


def pipeline_apply(
    stacked_params: Any,
    x_microbatches: jax.Array,  # [n_micro, micro_batch, ...feature dims]
    layer_fn: Callable[[jax.Array, Any], jax.Array],
    *,
    mesh: Mesh,
    stage_axis: str = "stage",
    batch_axes: Tuple[str, ...] = BATCH_AXES,
    remat: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Run every microbatch through all pipeline stages; returns
    (activations shaped like `x_microbatches`, aux_total scalar).

    `stacked_params` leaves have leading dim n_layers (divisible by the
    stage-axis size); `layer_fn(act, layer_params) -> (act, aux_scalar)`
    applies ONE layer, must be shape-preserving, and reports a per-layer
    aux scalar — e.g. the MoE load-balance loss (dense layers return a
    zero scalar). Microbatch dim 0 is the pipeline's time axis; dim 1
    (micro batch) is sharded over `batch_axes`.

    Aux contributions are gated to each stage's VALID window (the GPipe
    fill/drain steps feed clipped garbage that must not count), summed
    over this stage's layers and steps, psummed across stages, and
    averaged over microbatches — the microbatch-mean approximation of
    the full-batch aux every per-shard MoE implementation uses.
    """
    n_stages = mesh.shape[stage_axis]
    n_micro = x_microbatches.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"need >= {n_stages} microbatches to fill a {n_stages}-stage "
            f"pipeline, got {n_micro}"
        )
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(
            f"stacked layer count {n_layers} not divisible by the "
            f"{stage_axis}-axis size {n_stages}"
        )
    x_rank = x_microbatches.ndim

    per_layer = layer_fn
    if remat:
        per_layer = jax.checkpoint(per_layer)

    def run_local_layers(act, params_local):
        def body(carry, layer):
            a, aux = carry
            a, da = per_layer(a, layer)
            return (a, aux + da), None

        (act, aux), _ = jax.lax.scan(
            body, (act, jnp.zeros((), jnp.float32)), params_local)
        return act, aux

    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    n_steps = n_micro + n_stages - 1

    def pipelined(params_local, x_mub):
        stage = jax.lax.axis_index(stage_axis)
        out_buf = jnp.zeros_like(x_mub)
        act = jnp.zeros_like(x_mub[0])

        def step(carry, i):
            act, out_buf, aux_acc = carry
            # stage 0 ingests microbatch i (clipped: trailing drain steps
            # feed garbage that never reaches an output slot)
            inp = jax.lax.dynamic_index_in_dim(
                x_mub, jnp.clip(i, 0, n_micro - 1), 0, keepdims=False
            )
            act = jnp.where(stage == 0, inp, act)
            act, aux = run_local_layers(act, params_local)
            # stage s does REAL work on microbatch i-s; fill/drain steps
            # process clipped garbage whose aux must not count
            valid = jnp.logical_and(i - stage >= 0, i - stage < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # last stage banks finished microbatch i-(n_stages-1)
            out_idx = jnp.clip(i - (n_stages - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, out_idx, 0, keepdims=False)
            bank = jnp.where(
                jnp.logical_and(stage == n_stages - 1, i >= n_stages - 1), act, cur
            )
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, bank, out_idx, 0)
            # rotate activations one ICI hop to the next stage
            act = jax.lax.ppermute(act, stage_axis, perm)
            return (act, out_buf, aux_acc), None

        (act, out_buf, aux_acc), _ = jax.lax.scan(
            step, (act, out_buf, jnp.zeros((), jnp.float32)),
            jnp.arange(n_steps, dtype=jnp.int32)
        )
        # every stage contributes its own layers' aux; mean over
        # microbatches approximates the full-batch value, pmean over the
        # batch axes makes it a true global (replicated) scalar
        aux_total = jax.lax.psum(aux_acc, stage_axis) / n_micro
        aux_total = jax.lax.pmean(aux_total, batch_axes)
        # leading singleton picks out this stage's copy; only the last
        # stage's buffer holds real outputs and the caller slices it.
        return out_buf[None], aux_total

    params_spec = jax.tree_util.tree_map(lambda _: P(stage_axis), stacked_params)
    x_spec = P(None, batch_axes, *([None] * (x_rank - 2)))
    out_spec = P(stage_axis, None, batch_axes, *([None] * (x_rank - 2)))

    out, aux = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=(out_spec, P()),
    )(stacked_params, x_microbatches)
    return out[-1], aux


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [n_micro, B/n_micro, ...]."""
    if x.shape[0] % n_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_microbatches} microbatches"
        )
    return x.reshape((n_microbatches, x.shape[0] // n_microbatches) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[n_micro, mb, ...] -> [n_micro*mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
