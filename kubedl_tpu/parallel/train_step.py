"""Sharded train step factory — pjit + NamedSharding, no hand-rolled
collectives.

Builds the full SPMD training step for a model: params/opt-state sharded by
the model's param_specs (fsdp/tensor axes), batch sharded over data+fsdp,
gradients and updates computed under jit with donated state so XLA reuses
the buffers in place. Collectives (psum for grads across data, all-gather /
reduce-scatter for fsdp params) are inserted by XLA from the shardings —
the scaling-book recipe, not an NCCL translation.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubedl_tpu.parallel.mesh import ShardingRules


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def make_train_step(
    loss_fn: Callable,  # (params, batch) -> scalar loss  [or (loss, aux)]
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_spec_tree: Any,
    batch_spec: P,
    rules: Optional[ShardingRules] = None,
    accum_steps: int = 1,
    has_aux: bool = False,
) -> Tuple[Callable, Callable]:
    """Returns (init_state, train_step), both jitted over the mesh.

    init_state(params) -> TrainState with sharded params/opt state.
    train_step(state, batch) -> (state, metrics) with donated state.
    accum_steps > 1 accumulates gradients over that many micro-steps
    before applying the update (optax.MultiSteps) — the HBM-for-batch
    trade when the global batch doesn't fit.
    """
    rules = rules or ShardingRules()
    if accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=accum_steps)
    param_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_spec_tree
    )
    # batch_spec may be one P or a pytree of Ps (e.g. (images, labels));
    # P subclasses tuple, so guard it as a leaf
    batch_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), batch_spec,
        is_leaf=lambda x: isinstance(x, P),
    )
    repl = NamedSharding(mesh, P())

    def _init(params):
        opt_state = tx.init(params)
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    # Optimizer moments have param shapes; with out_shardings unspecified
    # XLA propagates the params' shardings onto them.
    init_jit = jax.jit(_init, in_shardings=(param_sharding,))

    def _step(state: TrainState, batch):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
            aux = {}
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (
            TrainState(params=new_params, opt_state=new_opt, step=state.step + 1),
            {"loss": loss, "grad_norm": gnorm, **aux},
        )

    step_jit = jax.jit(
        _step,
        in_shardings=(None, batch_sharding),
        donate_argnums=(0,),
    )

    def init_state(params):
        params = jax.device_put(params, param_sharding)
        return init_jit(params)

    # AOT access (fit checks, ahead-of-time compiles): the inner jit
    # accepts abstract params and its compiled output_shardings give the
    # full TrainState sharding tree — eval_shape alone drops shardings,
    # so an AOT lower of step_jit with plain ShapeDtypeStructs would
    # silently measure a REPLICATED state (tests/test_aot_fit.py)
    init_state.jit = init_jit

    return init_state, step_jit
