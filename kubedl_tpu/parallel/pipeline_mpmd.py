"""MPMD cross-slice pipeline plumbing — stage plans, the serialized DCN
boundary, and the transports that join stage programs.

The single-program pipeline (parallel/pipeline.py) is SPMD: one compiled
program, activations rotated by ppermute, bounded by one slice's HBM. The
MPMD plane (the pipeline-parallelism paper, PAPERS.md arxiv 2412.14374)
breaks that ceiling: each pipeline stage is its OWN program on its own
slice, holding only its layer chunk + optimizer state, joined by async
send/recv of activations (forward) and activation-gradients (backward)
over DCN. This module is the program-independent half:

  * StagePlan / split_stage_params — which layers (and which of the
    embed / lm-head endcaps) each stage program owns;
  * encode_boundary / decode_boundary — the wire form of one boundary
    tensor batch: a JSON header recording dtype + shapes and a raw-uint8
    payload. dtype is RECORDED, never inferred: npz round-trips bf16 as
    an opaque |V2 void (the PR 6 serving handoff / PR 8 staged-reshard
    lesson), so the wire carries raw bytes + the dtype string and the
    decoder views them back. Mixed-dtype batches are refused — one
    buffer, one dtype, no silent casts;
  * Channel implementations — QueueChannel (in-process, tests/bench) and
    DirChannel (atomic file-per-message over a shared dir: the local
    executor's DCN analog, same discipline as the PR 8 control channel);
  * AsyncSender / Prefetcher — double-buffered transfers so stage s
    computes microbatch i while its send of i-1 and recv of i+1 are in
    flight (the barrier-free steady state).

The schedule that drives these lives in train/pipeline_runtime.py.
"""
from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubedl_tpu.api.validation import validate_pipeline_shapes

# ---------------------------------------------------------------------------
# stage plans
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StagePlan:
    """How one model splits into stage programs: contiguous equal layer
    chunks, embed on stage 0, final-norm + lm-head on the last stage."""

    n_layers: int
    n_stages: int
    n_microbatches: int

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages

    def layer_range(self, stage: int) -> Tuple[int, int]:
        if not (0 <= stage < self.n_stages):
            raise ValueError(f"stage {stage} out of range [0, {self.n_stages})")
        per = self.layers_per_stage
        return stage * per, (stage + 1) * per


def make_stage_plan(
    n_layers: int, n_stages: int, n_microbatches: int
) -> StagePlan:
    """Validated plan (shared shape rules: api/validation.py). The MPMD
    runtime implements plain 1F1B (interleave=1) — interleaving virtual
    stages is the intra-slice schedule's job (pipeline_apply_1f1b)."""
    errs = validate_pipeline_shapes(
        n_stages, n_microbatches, 1, n_layers=n_layers, path="pipeline_mpmd")
    if errs:
        raise ValueError("; ".join(errs))
    return StagePlan(
        n_layers=n_layers, n_stages=n_stages, n_microbatches=n_microbatches)


def split_stage_params(params: Dict, plan: StagePlan, stage: int) -> Dict:
    """Stage-local param subtree: this stage's layer list, plus the embed
    table (stage 0) and final norm + LM head (last stage). Works on the
    param pytree AND on a matching PartitionSpec pytree (it only slices
    the layer list and copies endcap leaves)."""
    lo, hi = plan.layer_range(stage)
    out: Dict[str, Any] = {"layers": list(params["layers"][lo:hi])}
    if stage == 0:
        out["embed"] = params["embed"]
    if stage == plan.n_stages - 1:
        out["final_norm"] = params["final_norm"]
        if "lm_head" not in params:
            # tied embeddings put the head's weights on stage 0 — a
            # cross-stage parameter the MPMD split cannot represent
            raise ValueError(
                "tie_embeddings is unsupported in the MPMD pipeline (the "
                "tied LM head lives on stage 0, the final norm on the "
                "last stage); use a separate lm_head")
        out["lm_head"] = params["lm_head"]
    return out


# ---------------------------------------------------------------------------
# serialized DCN boundary
# ---------------------------------------------------------------------------

_MAGIC = b"kdlpp1"


def encode_boundary(
    arrays: Sequence[np.ndarray], meta: Optional[Dict] = None
) -> bytes:
    """One boundary message: JSON header (dtype string, shapes, optional
    scalar meta) + raw-uint8 payload. All arrays must share ONE dtype —
    mixed-dtype batches are refused rather than silently upcast (the
    decoder views one flat buffer back through one recorded dtype)."""
    arrays = [np.asarray(a) for a in arrays]
    if not arrays:
        raise ValueError("empty boundary message")
    dtypes = {str(a.dtype) for a in arrays}
    if len(dtypes) != 1:
        raise ValueError(
            f"mixed-dtype boundary refused: {sorted(dtypes)} — the raw "
            f"uint8 payload records ONE dtype; send separate messages")
    header = {
        "dtype": dtypes.pop(),
        "shapes": [list(a.shape) for a in arrays],
    }
    if meta:
        header["meta"] = meta
    hbytes = json.dumps(header).encode("utf-8")
    payload = b"".join(np.ascontiguousarray(a).tobytes() for a in arrays)
    return _MAGIC + len(hbytes).to_bytes(4, "big") + hbytes + payload


def decode_boundary(data: bytes) -> Tuple[List[np.ndarray], Dict]:
    """Inverse of encode_boundary: (arrays, meta). bf16 survives because
    the dtype STRING was recorded and ml_dtypes registers "bfloat16"
    with numpy — the payload is viewed, never re-interpreted."""
    if data[: len(_MAGIC)] != _MAGIC:
        raise ValueError("not a pipeline boundary message (bad magic)")
    off = len(_MAGIC)
    hlen = int.from_bytes(data[off:off + 4], "big")
    off += 4
    header = json.loads(data[off:off + hlen].decode("utf-8"))
    off += hlen
    import ml_dtypes  # noqa: F401 — registers bfloat16 et al with numpy

    dtype = np.dtype(header["dtype"])
    arrays = []
    for shape in header["shapes"]:
        n = int(np.prod(shape)) if shape else 1
        nbytes = n * dtype.itemsize
        arrays.append(
            np.frombuffer(data[off:off + nbytes], dtype=dtype).reshape(shape))
        off += nbytes
    if off != len(data):
        raise ValueError(
            f"boundary payload length mismatch: {len(data) - off} trailing "
            f"bytes (truncated or corrupt message)")
    return arrays, header.get("meta") or {}


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------


class QueueChannel:
    """In-process channel: tag -> bytes, delivered exactly once. Both
    endpoints hold the same object (the in-process lane of the MPMD
    harness; tests, bench, dryrun)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._msgs: Dict[str, bytes] = {}

    def send(self, tag: str, data: bytes) -> None:
        with self._cond:
            if tag in self._msgs:
                raise ValueError(f"duplicate boundary tag {tag!r}")
            self._msgs[tag] = data
            self._cond.notify_all()

    def recv(self, tag: str, timeout: float = 60.0) -> bytes:
        deadline = time.monotonic() + timeout
        with self._cond:
            while tag not in self._msgs:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"boundary recv timed out waiting for {tag!r}")
                self._cond.wait(left)
            return self._msgs.pop(tag)


_TAG_SAFE = re.compile(r"[^A-Za-z0-9._:-]")


class DirChannel:
    """File-per-message channel over a shared directory — the local
    executor's stand-in for a DCN link (write-to-temp + atomic rename,
    the same never-observe-a-partial-file discipline as the PR 8 reshard
    control channel). Works across processes; the two-process parity
    test rides it."""

    def __init__(self, path: str, poll_s: float = 0.005) -> None:
        self.path = path
        self.poll_s = poll_s
        os.makedirs(path, exist_ok=True)

    def _fname(self, tag: str) -> str:
        return os.path.join(self.path, _TAG_SAFE.sub("_", tag) + ".msg")

    def purge(self) -> int:
        """Delete every pending message — a RESTARTING receiver calls
        this on the dirs it receives on, so messages a crashed previous
        incarnation left behind cannot be consumed as current data
        (tags restart from 1 after a restart). Returns the count."""
        n = 0
        try:
            names = os.listdir(self.path)
        except OSError:
            return 0
        for name in names:
            if name.endswith(".msg"):
                try:
                    os.unlink(os.path.join(self.path, name))
                    n += 1
                except OSError:
                    pass  # a concurrent recv consumed it
        return n

    def send(self, tag: str, data: bytes) -> None:
        final = self._fname(tag)
        tmp = final + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    def recv(self, tag: str, timeout: float = 60.0) -> bytes:
        fname = self._fname(tag)
        deadline = time.monotonic() + timeout
        while True:
            try:
                with open(fname, "rb") as f:
                    data = f.read()
                os.unlink(fname)
                return data
            except FileNotFoundError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"boundary recv timed out waiting for {tag!r} "
                        f"in {self.path}") from None
                time.sleep(self.poll_s)


class AsyncSender:
    """Double-buffered async send: `send` enqueues and returns, a worker
    thread drains — compute of microbatch i overlaps the transfer of
    i-1. `depth` bounds in-flight messages (2 = classic double buffer);
    a full queue applies backpressure instead of unbounded host RAM.
    Transport errors surface on the NEXT send/flush, never vanish."""

    def __init__(self, channel, depth: int = 2) -> None:
        self._channel = channel
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self.sent_bytes = 0

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                tag, data = item
                try:
                    self._channel.send(tag, data)
                except BaseException as e:  # noqa: BLE001 — reraised on send/flush
                    self._err = e
            finally:
                self._q.task_done()

    def _check(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError(f"async boundary send failed: {err}") from err

    def send(self, tag: str, data: bytes) -> None:
        self._check()
        self.sent_bytes += len(data)
        self._q.put((tag, data))

    def flush(self) -> None:
        self._q.join()
        self._check()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=5)
        self._check()


class Prefetcher:
    """Double-buffered async recv: given the (deterministic) tag order a
    stage will consume, a worker thread keeps up to `depth` messages
    fetched ahead — the recv of microbatch i+1 is in flight while i is
    computing. `get(tag)` must be called in the expected order."""

    def __init__(self, channel, depth: int = 2, timeout: float = 60.0) -> None:
        self._channel = channel
        self._timeout = timeout
        self._pending: "queue.Queue" = queue.Queue()
        self._ready: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self.recv_bytes = 0

    def _run(self) -> None:
        while True:
            tag = self._pending.get()
            if tag is None:
                return
            try:
                data = self._channel.recv(tag, timeout=self._timeout)
                self._ready.put((tag, data, None))
            except BaseException as e:  # noqa: BLE001 — delivered via get()
                self._ready.put((tag, None, e))
                return

    def expect(self, tags: Sequence[str]) -> None:
        for tag in tags:
            self._pending.put(tag)

    def get(self, tag: str) -> bytes:
        got_tag, data, err = self._ready.get(timeout=self._timeout + 5)
        if err is not None:
            raise RuntimeError(f"async boundary recv failed: {err}") from err
        if got_tag != tag:
            raise RuntimeError(
                f"boundary recv out of order: expected {tag!r}, got "
                f"{got_tag!r} (Prefetcher.get must follow expect order)")
        self.recv_bytes += len(data)
        return data

    def close(self) -> None:
        self._pending.put(None)
        self._thread.join(timeout=5)
