"""The authenticated socket transport plane (docs/transport.md).

ONE message plane for everything that used to ride files-on-a-volume:
RESIZE control messages (sched/capacity.py), MPMD pipeline boundary
activations/grads (train/pipeline_runtime.py), serving KV handoffs
(serving/router.py), and staged-reshard block fetches
(train/reshard_runtime.py). Dependency-free (stdlib sockets), token
authenticated, length-prefix framed; `DirChannel` survives as the
local-executor test transport, selected via ``KUBEDL_TRANSPORT``.
"""
from kubedl_tpu.transport.blocks import fetch_staging, serve_staging
from kubedl_tpu.transport.control import (
    SocketControlRouter,
    SocketReshardControl,
)
from kubedl_tpu.transport.metrics import transport_metrics
from kubedl_tpu.transport.plane import (
    ENV_BIND,
    ENV_TOKEN,
    ENV_TRANSPORT,
    SocketChannel,
    TransportError,
    TransportPlane,
    plane_from_env,
)

__all__ = [
    "ENV_BIND",
    "ENV_TOKEN",
    "ENV_TRANSPORT",
    "SocketChannel",
    "SocketControlRouter",
    "SocketReshardControl",
    "TransportError",
    "TransportPlane",
    "fetch_staging",
    "plane_from_env",
    "serve_staging",
    "transport_metrics",
]
