"""Socket backend for the RESIZE control channel.

The dir backend (executor post_control -> KUBEDL_CONTROL_DIR ->
reshard_runtime.ReshardControl) only works when the operator and the pod
share a filesystem — which is why kube-mode resizes fell back to the
checkpoint path. This module is the same protocol over the transport
plane, keeping BOTH existing seams intact:

  * operator side — ``SocketControlRouter.post`` matches the
    ``post_fn(namespace, pod, message) -> reply path | None`` contract
    of ``CapacityScheduler.attach_control``: it sends the message over
    the plane and returns a LOCAL spool path; when the pod's reply
    arrives it is written there atomically, so ``_reshard_pass`` keeps
    polling files and the reply schema is byte-for-byte the dir
    backend's.
  * pod side — ``SocketReshardControl`` is a drop-in peer of
    ``ReshardControl`` (``poll()`` at step boundaries, ``reply()``),
    reading the plane's ``control`` channel instead of a directory.

The message carries ``reply``/``reply_addr`` so the pod knows where to
send the answer — the operator's own listen address rides along the way
the reply filename does on the dir backend. Control planes run with
``latch=False``: pods legitimately restart between resizes, and reply
matching is per-tag, so stale incarnations cannot cross-talk.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from kubedl_tpu.transport.plane import TransportError, TransportPlane
from kubedl_tpu.analysis.witness import new_lock

log = logging.getLogger("kubedl_tpu.transport")

CONTROL_CHANNEL = "control"
CONTROL_REPLY_CHANNEL = "control-reply"


class SocketControlRouter:
    """Operator-side control post over the plane: dial each pod's
    transport address, spool replies as local files."""

    def __init__(
        self,
        plane: TransportPlane,
        spool_dir: str,
        addr_for: Callable[[str, str], Optional[str]],
        reply_ttl_s: float = 600.0,
        epoch_fn: Optional[Callable[[], int]] = None,
    ) -> None:
        self.plane = plane
        self.spool_dir = spool_dir
        self.addr_for = addr_for  # (namespace, pod) -> host:port | None
        # leader fencing (docs/ha.md): stamp the current epoch into
        # every control message so pods refuse a deposed operator's
        # posts; None (tests, non-HA mode) stamps epoch 0 = unfenced
        self.epoch_fn = epoch_fn
        # a pod killed mid-resize never replies: without a TTL its
        # pending entry (and a very late stale reply's spool write)
        # would outlive the scheduler's own deadline forever
        self.reply_ttl_s = reply_ttl_s
        self._lock = new_lock("transport.control.SocketControlRouter._lock")
        self._seq = 0
        self._pending: Dict[str, tuple] = {}  # tag -> (spool path, deadline)
        os.makedirs(spool_dir, exist_ok=True)
        plane.subscribe(CONTROL_REPLY_CHANNEL, self._on_reply)

    def _prune(self, now: float) -> None:
        """Caller holds the lock."""
        dead = [t for t, (_, dl) in self._pending.items() if dl <= now]
        for t in dead:
            del self._pending[t]

    def post(self, namespace: str, name: str,
             message: Dict) -> Optional[str]:
        """The attach_control post_fn: returns the spool path the reply
        will land at, or None when the pod is unreachable (the scheduler
        then falls back closed to the checkpoint path)."""
        addr = self.addr_for(namespace, name)
        if not addr:
            return None
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            self._seq += 1
            tag = f"{namespace}_{name}-{self._seq:06d}"
        path = os.path.join(self.spool_dir, f"reply-{tag}.json")
        msg = dict(message)
        msg["reply"] = tag
        msg["reply_addr"] = self.plane.bound_addr
        msg["epoch"] = int(self.epoch_fn()) if self.epoch_fn else 0
        with self._lock:
            self._pending[tag] = (path, now + self.reply_ttl_s)
        try:
            self.plane.send(
                addr, CONTROL_CHANNEL, tag,
                json.dumps(msg).encode("utf-8"))
        except (TransportError, TimeoutError) as e:
            with self._lock:
                self._pending.pop(tag, None)
            log.warning("control post to %s/%s at %s failed: %s",
                        namespace, name, addr, e)
            return None
        return path

    def _on_reply(self, tag: str, data: bytes) -> None:
        with self._lock:
            entry = self._pending.pop(tag, None)
            if entry is not None and entry[1] <= time.monotonic():
                entry = None  # expired: a stale reply must not spool
        if entry is None:
            return  # a reply nobody is waiting for (duplicate / stale)
        path = entry[0]
        tmp = path + ".tmp"
        try:
            # the payload IS the reply JSON the pod wrote — spooled
            # atomically so _reshard_pass never parses a partial reply
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            log.warning("could not spool control reply %s", tag)


class SocketReshardControl:
    """Pod-side control endpoint over the plane — the socket peer of
    reshard_runtime.ReshardControl (same poll()/reply() surface, so the
    trainer's reshard ladder is transport-blind)."""

    def __init__(self, plane: TransportPlane) -> None:
        self.plane = plane
        self._channel = plane.channel(CONTROL_CHANNEL)
        # leader fencing (docs/ha.md): highest epoch seen so far — a
        # message stamped with a LOWER (non-zero) epoch comes from a
        # deposed operator and is refused loudly, never acted on
        self._max_epoch = 0
        self.stale_epoch_refusals = 0

    def poll(self) -> Optional[dict]:
        """Earliest pending control message, or None. Cheap enough for a
        per-step call (one inbox pop, no I/O)."""
        while True:
            got = self._channel.poll()
            if got is None:
                return None
            _, data = got
            try:
                msg = json.loads(data.decode("utf-8"))
            except ValueError:
                continue  # corrupt frame payload: skip, never crash a step
            if not isinstance(msg, dict):
                continue
            epoch = int(msg.get("epoch", 0) or 0)
            if epoch and epoch < self._max_epoch:
                self.stale_epoch_refusals += 1
                log.error(
                    "control message REFUSED: fencing epoch %d is stale "
                    "(a newer leader at epoch %d has spoken) — a deposed "
                    "operator is still posting; dropping %r",
                    epoch, self._max_epoch, msg.get("reply"))
                continue
            if epoch > self._max_epoch:
                self._max_epoch = epoch
            return msg

    def reply(self, msg: dict, **payload) -> None:
        tag = msg.get("reply")
        addr = msg.get("reply_addr")
        if not tag or not addr:
            log.warning("control message carries no reply route; dropping")
            return
        try:
            self.plane.send(
                addr, CONTROL_REPLY_CHANNEL, str(tag),
                json.dumps(payload).encode("utf-8"))
        except (TransportError, TimeoutError) as e:
            # same contract as ReshardControl.reply: log, never raise —
            # a lost reply surfaces as the scheduler's deadline fallback
            log.warning("could not send reshard reply %s: %s", tag, e)
