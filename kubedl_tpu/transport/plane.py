"""The socket message plane — framing, auth, channels, reconnect.

One ``TransportPlane`` per process endpoint: it LISTENS on one TCP port
(``KUBEDL_TRANSPORT_BIND``) and DIALS any number of peers, multiplexing
named logical channels over per-peer connections. The wire carries the
existing header+raw-uint8 payloads (pipeline ``encode_boundary`` bytes,
serialized KV npz, control JSON) OPAQUELY — the plane moves bytes, the
consumers keep their own encodings, so the bf16/|V2 discipline the
boundary and handoff formats already pin carries over unchanged.

Frame format (all integers big-endian):

    magic(4)=KDTP | type(1) | header_len(4) | header JSON | payload_len(8) | payload

Types: HELLO (token + boot id, first frame of every connection), WELCOME
(the accept side echoes ITS boot id), MSG ({channel, tag, boot, seq}),
ACK (per-MSG, the exactly-once commit point), REJECT (auth refusal),
PING/PONG (heartbeats). A frame that stops mid-payload is a torn frame:
the reader drops the connection and nothing is committed — a message is
either fully in the inbox or absent, the atomic-rename discipline of
``DirChannel`` restated for sockets.

Auth: every connection's HELLO carries the shared per-job token
(``KUBEDL_TRANSPORT_TOKEN``), compared CONSTANT-TIME at accept
(hmac.compare_digest); a bad token gets REJECT + close and a counter,
and no frame from an unauthenticated connection is ever committed.

Exactly-once: the dialer holds a per-peer lock (one in-flight MSG per
connection), waits for the ACK, and on a dropped connection reconnects
with bounded exponential backoff and RESENDS the frame; the accept side
dedups by (channel, tag) before committing, so a resend of a message
whose ACK was lost is dropped, not double-delivered. ``AsyncSender`` /
``Prefetcher`` (parallel/pipeline_mpmd.py) layer pipelining on top.

Boot ids: each plane stamps a random incarnation id into HELLO/WELCOME
and every MSG. With ``latch=True`` (the default — pipeline semantics) a
peer's id is latched on first contact and a CHANGE is refused loudly on
both sides: the dialer refuses to reconnect to a restarted listener,
and a restarted sender's message is REJECTed (its send raises — never
ACKed, nothing committed) while the receiving channel poisons itself so
pending recvs fail too — the PR 9 stale-incarnation guarantee, carried
over.
Planes whose peers legitimately restart between messages (the operator's
control router) pass ``latch=False``.
"""
from __future__ import annotations

import hmac
import json
import os
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

from kubedl_tpu.transport.metrics import transport_metrics
from kubedl_tpu.analysis.witness import new_lock

ENV_TRANSPORT = "KUBEDL_TRANSPORT"  # socket | dir
ENV_TOKEN = "KUBEDL_TRANSPORT_TOKEN"
ENV_BIND = "KUBEDL_TRANSPORT_BIND"

_MAGIC = b"KDTP"
_HELLO, _WELCOME, _MSG, _ACK, _REJECT, _PING, _PONG = range(1, 8)
# sanity bounds: a corrupt length prefix must fail the frame, not
# allocate gigabytes
_MAX_HEADER = 1 << 20
_MAX_PAYLOAD = 1 << 34


class TransportError(RuntimeError):
    """Loud transport failure — auth refused, peer incarnation changed,
    reconnect budget exhausted. Never swallowed into silent data loss."""


class _ConnClosed(ConnectionError):
    """Peer closed cleanly BETWEEN frames — not a torn frame."""


def _recv_exact(sock: socket.socket, n: int, eof_ok: bool = False) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            if eof_ok and not buf:
                raise _ConnClosed("peer closed")
            raise ConnectionError(
                f"connection closed {len(buf)}/{n} bytes into a frame")
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(sock: socket.socket, ftype: int, header: Dict,
                payload: bytes = b"") -> None:
    hbytes = json.dumps(header).encode("utf-8")
    sock.sendall(
        _MAGIC + bytes([ftype]) + struct.pack(">I", len(hbytes)) + hbytes
        + struct.pack(">Q", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Tuple[int, Dict, bytes]:
    head = _recv_exact(sock, 9, eof_ok=True)
    if head[:4] != _MAGIC:
        raise ConnectionError("bad frame magic")
    ftype = head[4]
    hlen = struct.unpack(">I", head[5:9])[0]
    if hlen > _MAX_HEADER:
        raise ConnectionError(f"frame header length {hlen} out of bounds")
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    plen = struct.unpack(">Q", _recv_exact(sock, 8))[0]
    if plen > _MAX_PAYLOAD:
        raise ConnectionError(f"frame payload length {plen} out of bounds")
    return ftype, header, _recv_exact(sock, plen)


class _Inbox:
    """One logical channel's receive side: tag -> payload (insertion
    ordered), exactly-once dedup, and the sender-boot latch."""

    def __init__(self, latch: bool) -> None:
        self._cond = threading.Condition()
        self._msgs: Dict[str, bytes] = {}
        self._delivered: Dict[str, None] = {}  # bounded tag memory
        self._boot: Optional[str] = None
        self._err: Optional[TransportError] = None
        self._latch = latch

    def commit(self, tag: str, data: bytes, boot: str) -> str:
        """Deliver one message; returns "ok", "dup" (an already-committed
        resend — the caller ACKs, first copy won), or "stale" (a changed
        sender incarnation — the caller must REJECT, never ACK)."""
        with self._cond:
            if self._latch and boot:
                if self._boot is None:
                    self._boot = boot
                elif boot != self._boot:
                    # a restarted sender: poison the channel so every
                    # pending and future recv fails loud (the consumer's
                    # gang restart drains it), and refuse the stale data
                    self._err = TransportError(
                        f"message {tag!r} carries peer incarnation "
                        f"{boot!r} != latched {self._boot!r} — the peer "
                        f"restarted; refusing its messages")
                    transport_metrics.on_stale_boot()
                    self._cond.notify_all()
                    return "stale"
            if tag in self._delivered:
                return "dup"
            self._delivered[tag] = None
            if len(self._delivered) > 8192:
                self._delivered.pop(next(iter(self._delivered)))
            self._msgs[tag] = data
            self._cond.notify_all()
            return "ok"

    def recv(self, tag: str, timeout: float) -> bytes:
        deadline = time.monotonic() + timeout
        with self._cond:
            while tag not in self._msgs:
                if self._err is not None:
                    raise self._err
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"transport recv timed out waiting for {tag!r}")
                self._cond.wait(left)
            return self._msgs.pop(tag)

    def pop_any(self) -> Optional[Tuple[str, bytes]]:
        with self._cond:
            if self._err is not None:
                raise self._err
            if not self._msgs:
                return None
            tag = next(iter(self._msgs))
            return tag, self._msgs.pop(tag)

    def take(self, tag: str) -> Optional[bytes]:
        with self._cond:
            return self._msgs.pop(tag, None)

    def purge(self) -> int:
        with self._cond:
            n = len(self._msgs)
            self._msgs.clear()
            return n


class _Peer:
    """One cached outbound connection: dial + HELLO/WELCOME handshake,
    synchronous MSG->ACK sends under a lock, reconnect with bounded
    exponential backoff and resend on failure."""

    def __init__(self, plane: "TransportPlane", addr: str) -> None:
        self.plane = plane
        self.addr = addr
        self.lock = new_lock("transport.plane._Peer.lock")
        self.sock: Optional[socket.socket] = None
        self.boot: Optional[str] = None  # latched listener incarnation
        self._seq = 0

    # -- connection management (caller holds self.lock) -----------------

    def _dial_once(self) -> socket.socket:
        host, _, port = self.addr.rpartition(":")
        sock = socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self.plane.io_timeout)
        sock.settimeout(self.plane.io_timeout)
        try:
            _send_frame(sock, _HELLO, {
                "token": self.plane.token, "boot": self.plane.boot_id,
                "peer": self.plane.service})
            ftype, header, _ = _recv_frame(sock)
        except BaseException:
            sock.close()
            raise
        if ftype == _REJECT:
            sock.close()
            raise TransportError(
                f"peer {self.addr} rejected the connection: "
                f"{header.get('error', 'auth')}")
        if ftype != _WELCOME:
            sock.close()
            raise ConnectionError(f"expected WELCOME, got frame {ftype}")
        boot = str(header.get("boot", ""))
        if self.plane.latch and self.boot is not None and boot != self.boot:
            sock.close()
            transport_metrics.on_stale_boot()
            raise TransportError(
                f"peer {self.addr} came back as incarnation {boot!r} != "
                f"latched {self.boot!r} — it restarted; refusing to "
                f"resume (restart this side for a clean rendezvous)")
        self.boot = boot
        return sock

    def _connect(self, budget_s: float, reconnect: bool) -> None:
        """Dial with exponential backoff until `budget_s` is spent; an
        auth/incarnation refusal is permanent and raises immediately."""
        deadline = time.monotonic() + budget_s
        backoff = self.plane.retry_backoff
        attempt = 0
        t0 = time.perf_counter()
        while True:
            attempt += 1
            try:
                self.sock = self._dial_once()
                transport_metrics.on_connect(reconnect=reconnect)
                self.plane._trace(
                    "transport.reconnect" if reconnect else "transport.connect",
                    duration_s=time.perf_counter() - t0,
                    peer=self.addr, attempts=attempt)
                return
            except TransportError:
                raise  # auth / incarnation: retrying cannot fix it
            except OSError as e:
                if time.monotonic() + backoff > deadline:
                    raise TransportError(
                        f"could not {'re' if reconnect else ''}connect to "
                        f"{self.addr} after {attempt} attempts over "
                        f"{budget_s:.1f}s: {e}") from e
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)

    def _drop(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    # -- requests --------------------------------------------------------

    def send_msg(self, channel: str, tag: str, data: bytes,
                 timeout: Optional[float] = None) -> None:
        """Send one message and wait for its ACK; on a dropped
        connection, reconnect and RESEND (the accept side dedups)."""
        timeout = self.plane.io_timeout if timeout is None else timeout
        # kubedl-analysis: allow[lock-io] one in-flight MSG->ACK per connection IS this lock's contract: it serializes the socket, never guards shared state, and send timeouts bound the hold
        with self.lock:
            self._seq += 1
            seq = self._seq
            header = {"channel": channel, "tag": tag,
                      "boot": self.plane.boot_id, "seq": seq}
            for resend in range(self.plane.max_resends + 1):
                try:
                    if self.sock is None:
                        self._connect(
                            self.plane.dial_budget_s if not resend
                            else self.plane.reconnect_budget_s,
                            reconnect=bool(resend))
                    self.sock.settimeout(timeout)
                    _send_frame(self.sock, _MSG, header, data)
                    while True:
                        ftype, h, _ = _recv_frame(self.sock)
                        if ftype == _ACK and int(h.get("seq", -1)) == seq:
                            break
                        if ftype == _PONG:
                            continue  # a late heartbeat reply
                        if ftype == _REJECT:
                            # permanent refusal (stale incarnation):
                            # resending cannot fix it — fail loud NOW
                            self._drop()
                            raise TransportError(
                                f"peer {self.addr} refused "
                                f"{channel}/{tag}: "
                                f"{h.get('error', 'rejected')}")
                        raise ConnectionError(
                            f"expected ACK {seq}, got frame {ftype}")
                    transport_metrics.on_message(channel, "send", len(data))
                    return
                except (OSError, ConnectionError, socket.timeout):
                    self._drop()
                    if resend >= self.plane.max_resends:
                        raise TransportError(
                            f"send of {channel}/{tag} to {self.addr} failed "
                            f"after {resend + 1} attempts") from None

    def ping(self) -> None:
        # kubedl-analysis: allow[lock-io] heartbeats ride the same per-connection serialization lock as send_msg; io_timeout bounds the hold
        with self.lock:
            if self.sock is None:
                return  # nothing to keep alive
            try:
                self.sock.settimeout(self.plane.io_timeout)
                _send_frame(self.sock, _PING, {})
                ftype, _, _ = _recv_frame(self.sock)
                if ftype != _PONG:
                    raise ConnectionError(f"expected PONG, got {ftype}")
                transport_metrics.on_heartbeat()
            except (OSError, ConnectionError, socket.timeout):
                self._drop()  # next send reconnects (and resends)

    def close(self) -> None:
        with self.lock:
            self._drop()


class SocketChannel:
    """One named logical channel on a plane — the socket peer of
    ``QueueChannel``/``DirChannel``: ``send(tag, data)`` dials the fixed
    peer address, ``recv(tag, timeout)`` reads the LOCAL plane's inbox.
    The payload bytes are carried opaquely (byte-identical boundary
    encoding is the consumer's contract, pinned in tests)."""

    def __init__(self, plane: "TransportPlane", name: str,
                 peer_addr: str = "") -> None:
        self.plane = plane
        self.name = name
        self.peer_addr = peer_addr

    def send(self, tag: str, data: bytes) -> None:
        if not self.peer_addr:
            raise TransportError(
                f"channel {self.name!r} has no peer address to send to")
        self.plane.send(self.peer_addr, self.name, tag, data)

    def recv(self, tag: str, timeout: float = 60.0) -> bytes:
        return self.plane.recv(self.name, tag, timeout)

    def poll(self) -> Optional[Tuple[str, bytes]]:
        """Earliest pending (tag, payload), or None — the control
        channel's non-blocking step-boundary check."""
        return self.plane._inbox(self.name).pop_any()

    def purge(self) -> int:
        return self.plane._inbox(self.name).purge()


class TransportPlane:
    """One process endpoint of the message plane: a listener plus cached
    outbound peer connections, multiplexing named channels."""

    def __init__(
        self,
        token: str = "",
        service: str = "",
        latch: bool = True,
        io_timeout: float = 60.0,
        dial_budget_s: float = 60.0,
        reconnect_budget_s: float = 10.0,
        retry_backoff: float = 0.05,
        max_resends: int = 4,
        heartbeat_s: float = 0.0,
        tracer=None,
    ) -> None:
        self.token = token
        self.service = service or f"pid-{os.getpid()}"
        self.latch = latch
        self.io_timeout = io_timeout
        self.dial_budget_s = dial_budget_s
        self.reconnect_budget_s = reconnect_budget_s
        self.retry_backoff = retry_backoff
        self.max_resends = max_resends
        self.heartbeat_s = heartbeat_s
        self.boot_id = uuid.uuid4().hex[:12]
        self.bound_addr = ""
        self._tracer = tracer
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._conns: list = []
        self._peers: Dict[str, _Peer] = {}
        self._inboxes: Dict[str, _Inbox] = {}
        self._subs: Dict[str, Callable[[str, bytes], None]] = {}
        self._lock = new_lock("transport.plane.TransportPlane._lock")
        self._stop = threading.Event()

    def _trace(self, name: str, duration_s: float = 0.0, **attrs) -> None:
        """transport.connect / transport.reconnect spans on the job's
        flight-recorder timeline (lazy tracer_from_env: exports only when
        the executor injected KUBEDL_TRACE_DIR, ring-only otherwise)."""
        if self._tracer is None:
            try:
                from kubedl_tpu.obs.trace import tracer_from_env

                self._tracer = tracer_from_env(self.service)
            except Exception:  # noqa: BLE001 — tracing must never block I/O
                self._tracer = False
        if self._tracer:
            try:
                self._tracer.record(name, duration_s=duration_s, **attrs)
            except Exception:  # noqa: BLE001 — tracing must never block I/O
                pass

    # -- listen side -----------------------------------------------------

    def listen(self, addr: str = "0.0.0.0:0") -> str:
        """Bind + start the accept loop; returns the bound host:port
        (the port resolved when `addr` asked for :0)."""
        host, _, port = addr.rpartition(":")
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port or 0)))
        srv.listen(64)
        # timeout-based accept so close() can stop the loop and the
        # port frees promptly (a blocked accept pins the fd open)
        srv.settimeout(0.2)
        self._server = srv
        self.bound_addr = f"{host or '127.0.0.1'}:{srv.getsockname()[1]}"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"transport-{self.service}",
            daemon=True)
        self._accept_thread.start()
        if self.heartbeat_s > 0:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name=f"transport-hb-{self.service}")
            self._hb_thread.start()
        return self.bound_addr

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # closed
            conn.settimeout(None)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True).start()
        try:
            self._server.close()
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        """One accepted connection: HELLO (constant-time token check)
        then MSG/PING frames until close. A frame that stops partway is
        a TORN frame: the connection drops with nothing committed."""
        authed = False
        try:
            conn.settimeout(self.io_timeout)
            ftype, header, _ = _recv_frame(conn)
            if ftype != _HELLO or not hmac.compare_digest(
                    str(header.get("token", "")), self.token):
                # unauthenticated frames are dropped with a counter; the
                # REJECT lets the dialer fail loud instead of hanging
                transport_metrics.on_auth_failure()
                try:
                    _send_frame(conn, _REJECT, {"error": "auth"})
                except OSError:
                    pass
                return
            _send_frame(conn, _WELCOME, {"boot": self.boot_id})
            conn.settimeout(None)  # idle connections are fine
            authed = True
            while not self._stop.is_set():
                ftype, header, payload = _recv_frame(conn)
                if ftype == _PING:
                    _send_frame(conn, _PONG, {})
                    continue
                if ftype != _MSG:
                    continue  # unknown frame type: ignore, stay connected
                channel = str(header.get("channel", ""))
                tag = str(header.get("tag", ""))
                boot = str(header.get("boot", ""))
                inbox = self._inbox(channel)
                sub = self._subs.get(channel)
                status = inbox.commit(tag, payload, boot)
                if status == "stale":
                    # a restarted sender: REJECT (never ACK — the ACK is
                    # the commit point, and nothing was committed) so
                    # its send fails loud IMMEDIATELY instead of
                    # computing against a poisoned receiver
                    _send_frame(conn, _REJECT,
                                {"error": "stale-incarnation"})
                    return
                if status == "ok":
                    transport_metrics.on_message(channel, "recv", len(payload))
                    if sub is not None:
                        inbox.take(tag)  # the callback consumes it
                        try:
                            sub(tag, payload)
                        except Exception:  # noqa: BLE001 — a subscriber
                            pass  # bug must not kill the connection
                # ACK dedup'd resends too: the first copy WAS committed
                _send_frame(conn, _ACK, {"seq": header.get("seq")})
        except _ConnClosed:
            pass  # clean close between frames
        except (ConnectionError, OSError, ValueError):
            if authed:
                transport_metrics.on_torn_frame()
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            with self._lock:
                peers = list(self._peers.values())
            for p in peers:
                p.ping()

    # -- dial side -------------------------------------------------------

    def _peer(self, addr: str) -> _Peer:
        with self._lock:
            p = self._peers.get(addr)
            if p is None:
                p = self._peers[addr] = _Peer(self, addr)
            return p

    def send(self, addr: str, channel: str, tag: str, data: bytes,
             timeout: Optional[float] = None) -> None:
        self._peer(addr).send_msg(channel, tag, data, timeout)

    def recv(self, channel: str, tag: str, timeout: float = 60.0) -> bytes:
        return self._inbox(channel).recv(tag, timeout)

    def _inbox(self, channel: str) -> _Inbox:
        with self._lock:
            box = self._inboxes.get(channel)
            if box is None:
                box = self._inboxes[channel] = _Inbox(self.latch)
            return box

    def channel(self, name: str, peer_addr: str = "") -> SocketChannel:
        return SocketChannel(self, name, peer_addr)

    def subscribe(self, channel: str,
                  fn: Callable[[str, bytes], None]) -> None:
        """Route a channel's messages to a callback (run on the
        connection thread) instead of leaving them for recv()."""
        self._subs[channel] = fn

    def close(self) -> None:
        self._stop.set()
        # the accept loop owns the final server close (its blocked
        # accept() otherwise pins the fd — and the port — open)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
        elif self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._lock:
            conns, self._conns = self._conns, []
            peers = list(self._peers.values())
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for p in peers:
            p.close()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)


def plane_from_env(
    service: str = "",
    latch: bool = True,
    env: Optional[Dict[str, str]] = None,
) -> Optional[TransportPlane]:
    """Build + start this pod's plane from the executor-injected env
    (the way KUBEDL_CONTROL_DIR travels): None unless
    ``KUBEDL_TRANSPORT=socket``. Listens on ``KUBEDL_TRANSPORT_BIND``
    (default any-interface ephemeral) with ``KUBEDL_TRANSPORT_TOKEN``."""
    env = os.environ if env is None else env
    if env.get(ENV_TRANSPORT, "") != "socket":
        return None
    token = env.get(ENV_TOKEN, "")
    if not token:
        # an empty token would make hmac.compare_digest("", "") pass at
        # accept — i.e. an UNAUTHENTICATED plane. Refuse to listen: the
        # per-job isolation the plane advertises must not silently not
        # exist (the executor/controller always injects one)
        raise ValueError(
            "KUBEDL_TRANSPORT=socket requires a non-empty "
            "KUBEDL_TRANSPORT_TOKEN (the shared per-job auth secret)")
    plane = TransportPlane(
        token=token,
        service=service or env.get("POD_NAME", ""),
        latch=latch,
    )
    plane.listen(env.get(ENV_BIND, "0.0.0.0:0"))
    return plane
