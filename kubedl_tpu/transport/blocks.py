"""Staged-reshard block movement over the plane.

The staged-restart lane (train/reshard_runtime.py) stages src-<pod>.npz
shard blocks + digest markers in a SHARED directory — the checkpoint
volume. On a cluster without one, a restarting pod can instead FETCH the
peer staging files over the transport plane into a local dir and then
run the unchanged ``restore_staged`` validation against it: the digest
checks, exactly-once assembly, and the closed fallback to checkpoint
restore are all untouched — only the byte movement changes.

``serve_staging`` runs on the pod (or sidecar) that still holds the
staging dir; ``fetch_staging`` pulls ``manifest.json`` first (to learn
``old_pods``), then every marker + npz, verifying a per-file sha256
carried in the reply header before the atomic local write — a corrupt
or truncated transfer is refused, never handed to ``restore_staged``.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import uuid
from typing import Dict, Optional, Sequence

from kubedl_tpu.transport.plane import TransportError, TransportPlane

log = logging.getLogger("kubedl_tpu.transport")

FETCH_CHANNEL = "reshard-fetch"
DATA_CHANNEL = "reshard-data"

# only staging artifacts are servable — the fetch protocol must not be
# a read-anything file server on the pod
_SERVABLE = re.compile(r"^(manifest\.json|src-\d+\.(npz|json))$")


def serve_staging(plane: TransportPlane, reshard_dir: str) -> None:
    """Serve this pod's staging dir on the plane: each request names one
    staging file; the reply carries its bytes + sha256 (or found=False)."""

    def on_request(tag: str, data: bytes) -> None:
        try:
            req = json.loads(data.decode("utf-8"))
            name = str(req["name"])
            reply_addr = str(req["reply_addr"])
        except (ValueError, KeyError):
            return  # malformed request: nothing to reply to
        header: Dict = {"name": name, "found": False}
        blob = b""
        if _SERVABLE.match(name):
            try:
                with open(os.path.join(reshard_dir, name), "rb") as f:
                    blob = f.read()
                header["found"] = True
                header["sha256"] = hashlib.sha256(blob).hexdigest()
            except OSError:
                pass  # found stays False
        hbytes = json.dumps(header).encode("utf-8")
        payload = len(hbytes).to_bytes(4, "big") + hbytes + blob
        try:
            plane.send(reply_addr, DATA_CHANNEL, tag, payload)
        except (TransportError, TimeoutError) as e:
            log.warning("staging serve of %s failed: %s", name, e)

    plane.subscribe(FETCH_CHANNEL, on_request)


def _fetch_one(plane: TransportPlane, peer_addr: str, name: str,
               timeout: float) -> Optional[bytes]:
    tag = f"{name}-{uuid.uuid4().hex[:8]}"
    plane.send(peer_addr, FETCH_CHANNEL, tag, json.dumps(
        {"name": name, "reply_addr": plane.bound_addr}).encode("utf-8"))
    payload = plane.recv(DATA_CHANNEL, tag, timeout=timeout)
    hlen = int.from_bytes(payload[:4], "big")
    header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    blob = payload[4 + hlen:]
    if not header.get("found"):
        return None
    digest = hashlib.sha256(blob).hexdigest()
    if digest != header.get("sha256"):
        raise TransportError(
            f"staging file {name} arrived corrupt "
            f"(sha256 {digest[:12]} != advertised "
            f"{str(header.get('sha256'))[:12]})")
    return blob


def fetch_staging(
    plane: TransportPlane,
    peer_addr: str,
    reshard_dir: str,
    timeout: float = 30.0,
    peers: Optional[Sequence[str]] = None,
) -> int:
    """Pull a peer's published staging into the LOCAL `reshard_dir`;
    returns the number of files fetched. Raises TransportError (or
    TimeoutError) on any gap — the caller's ladder then falls back
    closed to checkpoint restore, exactly as a missing shared-volume
    staging would. The fetched dir goes through the SAME
    ``restore_staged`` digest/coverage validation as a local one.

    `peers` (optional) are EXTRA addresses that may also hold the same
    verified staging (a weight-tree fan-out leaves every committed relay
    with the full set, docs/weights.md): src files round-robin across
    the swarm, falling back to `peer_addr` when a swarm member lacks a
    file. The per-file sha256 check makes the source interchangeable —
    a peer can serve wrong bytes but never get them adopted. The
    manifest is always taken from `peer_addr` and written LAST."""
    manifest = _fetch_one(plane, peer_addr, "manifest.json", timeout)
    if manifest is None:
        raise TransportError(
            f"peer {peer_addr} has no published staging manifest")
    try:
        old_pods = int(json.loads(manifest.decode("utf-8"))["old_pods"])
    except (ValueError, KeyError) as e:
        raise TransportError(f"peer staging manifest unreadable: {e}") from e
    os.makedirs(reshard_dir, exist_ok=True)
    swarm = [peer_addr] + [p for p in (peers or ()) if p != peer_addr]
    # stream each file to disk as it arrives — buffering every pod's npz
    # would hold the whole staged model state in host RAM at once, on a
    # pod that is mid-restart. Only the manifest must wait until LAST:
    # its presence promises the staging is complete (the same
    # marker-then-manifest ordering the staging writer uses), so a fetch
    # that dies partway leaves a manifest-less dir restore_staged treats
    # as still-in-flight, never as committed.
    n = 1
    i = 0
    for pod in range(old_pods):
        for name in (f"src-{pod}.json", f"src-{pod}.npz"):
            src = swarm[i % len(swarm)]
            i += 1
            blob = _fetch_one(plane, src, name, timeout)
            if blob is None and src != peer_addr:
                # swarm member doesn't hold it (or dropped its staging)
                # — the publishing peer is the authority of last resort
                blob = _fetch_one(plane, peer_addr, name, timeout)
            if blob is None:
                raise TransportError(
                    f"peer {peer_addr} staging is missing {name}")
            _atomic_write(os.path.join(reshard_dir, name), blob)
            n += 1
    _atomic_write(os.path.join(reshard_dir, "manifest.json"), manifest)
    return n


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
