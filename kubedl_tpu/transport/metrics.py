"""Transport-plane counters (kubedl_transport_* families).

A module-level singleton, the `pipeline_metrics` pattern: every plane in
the process folds into one collector, the operator registers
``transport_metrics.snapshot`` with RuntimeMetrics unconditionally, and
the families render through metrics/prom.py on /metrics + /debug/vars.
Counters only — the plane must never block on its own accounting.
"""
from __future__ import annotations

import threading

from kubedl_tpu.analysis.witness import new_lock
from typing import Dict, Tuple


class TransportMetrics:
    """Thread-safe counters for every transport plane in the process."""

    def __init__(self) -> None:
        self._lock = new_lock("transport.metrics.TransportMetrics._lock")
        # (channel, dir) -> count/bytes; dir is "send" | "recv"
        self._messages: Dict[Tuple[str, str], int] = {}
        self._bytes: Dict[Tuple[str, str], int] = {}
        self._connects = 0
        self._reconnects = 0
        self._auth_failures = 0
        self._torn_frames = 0
        self._stale_boot = 0
        self._heartbeats = 0

    def on_message(self, channel: str, direction: str, nbytes: int) -> None:
        key = (channel, direction)
        with self._lock:
            self._messages[key] = self._messages.get(key, 0) + 1
            self._bytes[key] = self._bytes.get(key, 0) + int(nbytes)

    def on_connect(self, reconnect: bool = False) -> None:
        with self._lock:
            if reconnect:
                self._reconnects += 1
            else:
                self._connects += 1

    def on_auth_failure(self) -> None:
        with self._lock:
            self._auth_failures += 1

    def on_torn_frame(self) -> None:
        with self._lock:
            self._torn_frames += 1

    def on_stale_boot(self) -> None:
        with self._lock:
            self._stale_boot += 1

    def on_heartbeat(self) -> None:
        with self._lock:
            self._heartbeats += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "messages_total": {
                    f"{ch}/{d}": n for (ch, d), n in sorted(self._messages.items())
                },
                "bytes_total": {
                    f"{ch}/{d}": n for (ch, d), n in sorted(self._bytes.items())
                },
                "connects_total": self._connects,
                "reconnects_total": self._reconnects,
                "auth_failures_total": self._auth_failures,
                "torn_frames_total": self._torn_frames,
                "stale_boot_refusals_total": self._stale_boot,
                "heartbeats_total": self._heartbeats,
            }

    def reset(self) -> None:
        """Test isolation — zero every counter."""
        with self._lock:
            self._messages.clear()
            self._bytes.clear()
            self._connects = self._reconnects = 0
            self._auth_failures = self._torn_frames = 0
            self._stale_boot = self._heartbeats = 0


transport_metrics = TransportMetrics()
