"""kubedl-tpu CLI — run jobs locally or serve the operator.

    python -m kubedl_tpu.cli run -f examples/tf_job_mnist.yaml
    python -m kubedl_tpu.cli operator --metrics-port 8443 --workloads '*'
    python -m kubedl_tpu.cli validate -f job.yaml

Flag names keep parity with the reference's startup flags
(ref main.go:54-66, docs/startup_flags.md): --max-reconciles,
--gang-scheduler-name, --workloads; TPU-native additions: --tpu-slices.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import yaml

from kubedl_tpu.api.common import JobConditionType, has_condition, is_failed, is_succeeded
from kubedl_tpu.api.validation import ValidationError, validate as api_validate
from kubedl_tpu.core.leader import DEFAULT_LEASE_PATH
from kubedl_tpu.core.store import NotFound
from kubedl_tpu.operator import Operator, OperatorConfig
from kubedl_tpu.server import OperatorHTTPServer


def _load_manifests(path: str):
    with open(path) as f:
        return [m for m in yaml.safe_load_all(f) if m]


def _mk_operator(args) -> Operator:
    return Operator(
        OperatorConfig(
            max_reconciles=args.max_reconciles,
            enable_gang_scheduling=bool(args.tpu_slices) or args.gang,
            gang_scheduler_name=args.gang_scheduler_name,
            tpu_slices=args.tpu_slices,
            workloads=args.workloads,
            object_storage=args.object_storage,
            event_storage=args.event_storage,
            storage_db_path=args.storage_db_path,
            enable_leader_election=getattr(args, "enable_leader_election", False),
            leader_lease_path=getattr(args, "leader_lease_path", DEFAULT_LEASE_PATH),
            kube_api_url=getattr(args, "kube_api_url", ""),
            kube_namespace=getattr(args, "kube_namespace", "default"),
        )
    )


def cmd_run(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    op.start()
    server = None
    if args.metrics_port:
        server = OperatorHTTPServer(op, port=args.metrics_port)
        port = server.start()
        print(f"serving metrics/API on http://127.0.0.1:{port}")
    rc = 0
    try:
        jobs = [op.apply(m) for p in args.files for m in _load_manifests(p)]
        for job in jobs:
            print(f"applied {job.kind} {job.metadata.namespace}/{job.metadata.name}")
        deadline = time.monotonic() + args.timeout
        pending = {(j.kind, j.metadata.namespace, j.metadata.name) for j in jobs}
        last_report = 0.0
        while pending and time.monotonic() < deadline:
            for key in list(pending):
                kind, ns, name = key
                try:
                    fresh = op.store.get(kind, ns, name)
                except NotFound:
                    print(f"{kind} {ns}/{name}: deleted before completion")
                    pending.discard(key)
                    rc = 1
                    continue
                if is_succeeded(fresh.status):
                    print(f"{kind} {ns}/{name}: Succeeded")
                    pending.discard(key)
                elif is_failed(fresh.status):
                    cond = fresh.status.conditions[-1]
                    print(f"{kind} {ns}/{name}: Failed — {cond.message}")
                    pending.discard(key)
                    rc = 1
            if time.monotonic() - last_report > 5:
                last_report = time.monotonic()
                for kind, ns, name in pending:
                    phases = [
                        (p.metadata.name, p.status.phase.value)
                        for p in op.store.list("Pod", namespace=ns)
                        if p.metadata.labels.get("job-name") == name
                    ]
                    print(f"waiting on {kind} {ns}/{name}: pods={phases}")
            time.sleep(0.1)
        if pending:
            print(f"timed out waiting for: {sorted(pending)}")
            rc = 1
    finally:
        if server:
            server.stop()
        op.stop()
    return rc


def cmd_operator(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    # Construct the server BEFORE op.start(): its token validation can
    # raise (non-loopback bind without a token), and failing here must not
    # leave a leader lease held or manager threads running.
    server = OperatorHTTPServer(
        op, host=args.bind, port=args.metrics_port or 8443,
        token=getattr(args, "api_token", None),
    )
    if args.enable_leader_election:
        print(f"acquiring leadership lease at {args.leader_lease_path} ...")
    op.start()
    if op.elector is not None:
        print(f"elected leader as {op.elector.identity}")
    port = server.start()
    print(f"kubedl-tpu operator serving on http://{args.bind}:{port} "
          f"(kinds: {sorted(op.reconcilers)})")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        op.stop()
    return 0


def cmd_validate(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    rc = 0
    for path in args.files:
        for m in _load_manifests(path):
            kind = m.get("kind", "")
            canonical = op._kind_by_lower.get(kind.lower())
            if canonical is None:
                print(f"{path}: unknown kind {kind!r}")
                rc = 1
                continue
            engine = op.reconcilers[canonical]
            from kubedl_tpu.utils.serde import from_dict

            job = from_dict(engine.controller.job_type(), m)
            engine.controller.set_defaults(job)
            try:
                api_validate(job, engine.controller)
            except ValidationError as e:
                print(f"{path}: INVALID — {e}")
                rc = 1
                continue
            n = sum(int(s.replicas or 0) for s in engine.controller.replica_specs(job).values())
            print(f"{path}: {canonical} {job.metadata.name} ok ({n} replicas)")
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubedl-tpu")
    parser.add_argument("--max-reconciles", type=int, default=1)
    parser.add_argument("--workloads", default="*")
    parser.add_argument("--gang-scheduler-name", default="tpu-slice")
    parser.add_argument("--gang", action="store_true", help="enable gang scheduling")
    parser.add_argument("--tpu-slices", nargs="*", default=[],
                        help="TPU pool, e.g. v5e-8 v5p-32")
    # persistence flags (ref --object-storage/--event-storage, persist_controller.go:30-74)
    parser.add_argument("--object-storage", default="",
                        help="object history backend name, e.g. sqlite")
    parser.add_argument("--event-storage", default="",
                        help="event history backend name, e.g. sqlite")
    parser.add_argument("--storage-db-path", default=":memory:",
                        help="database path for the sqlite backend")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run job manifests to completion locally")
    p_run.add_argument("-f", "--files", nargs="+", required=True)
    p_run.add_argument("--timeout", type=float, default=600.0)
    p_run.add_argument("--metrics-port", type=int, default=0)
    p_run.set_defaults(fn=cmd_run)

    p_op = sub.add_parser("operator", help="serve the operator over HTTP")
    p_op.add_argument("--bind", default="127.0.0.1")
    p_op.add_argument("--metrics-port", type=int, default=8443)
    # ref main.go:56: leader election defaults ON for the deployed operator
    p_op.add_argument("--enable-leader-election", action=argparse.BooleanOptionalAction,
                      default=True)
    p_op.add_argument("--leader-lease-path", default=DEFAULT_LEASE_PATH)
    p_op.add_argument("--kube-api-url", default="",
                      help="reconcile real cluster objects through this "
                           "kube-apiserver ('in-cluster' = service account)")
    p_op.add_argument("--kube-namespace", default="default")
    p_op.add_argument("--api-token", default=None,
                      help="bearer token for the HTTP API (env KUBEDL_API_TOKEN); "
                           "REQUIRED for non-loopback --bind")
    p_op.set_defaults(fn=cmd_operator)

    p_val = sub.add_parser("validate", help="parse and default manifests")
    p_val.add_argument("-f", "--files", nargs="+", required=True)
    p_val.set_defaults(fn=cmd_validate)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
