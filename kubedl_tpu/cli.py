"""kubedl-tpu CLI — run jobs locally or serve the operator.

    python -m kubedl_tpu.cli run -f examples/tf_job_mnist.yaml
    python -m kubedl_tpu.cli operator --metrics-port 8443 --workloads '*'
    python -m kubedl_tpu.cli validate -f job.yaml

Flag names keep parity with the reference's startup flags
(ref main.go:54-66, docs/startup_flags.md): --max-reconciles,
--gang-scheduler-name, --workloads; TPU-native additions: --tpu-slices.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import yaml

from kubedl_tpu.api.common import is_failed, is_succeeded
from kubedl_tpu.api.validation import ValidationError, validate as api_validate
from kubedl_tpu.core.leader import DEFAULT_LEASE_PATH, data_root
from kubedl_tpu.core.store import NotFound
from kubedl_tpu.operator import Operator, OperatorConfig
from kubedl_tpu.server import OperatorHTTPServer


def _load_manifests(path: str):
    with open(path) as f:
        return [m for m in yaml.safe_load_all(f) if m]


def _kv_pairs(entries, value_type, flag, minimum=None, exclusive=False):
    """Parse repeated NAME=VALUE flags (--tenant-weight / --tenant-cap),
    rejecting out-of-range values at startup — a negative weight would
    silently corrupt every tenant's fair share (sched/quota.py)."""
    out = {}
    for entry in entries or []:
        name, sep, val = entry.partition("=")
        if not sep or not name:
            raise SystemExit(f"error: {flag} expects NAME=VALUE, got {entry!r}")
        try:
            out[name] = value_type(val)
        except ValueError:
            raise SystemExit(f"error: {flag} {entry!r}: bad value {val!r}")
        if isinstance(out[name], float) and not math.isfinite(out[name]):
            # nan compares False against any bound below and would
            # poison every tenant's computed fair share downstream
            raise SystemExit(f"error: {flag} {entry!r}: value must be finite")
        if minimum is not None and (
            out[name] <= minimum if exclusive else out[name] < minimum
        ):
            bound = f"> {minimum}" if exclusive else f">= {minimum}"
            raise SystemExit(f"error: {flag} {entry!r}: value must be {bound}")
    return out


def _mk_operator(args) -> Operator:
    return Operator(
        OperatorConfig(
            max_reconciles=args.max_reconciles,
            enable_gang_scheduling=bool(args.tpu_slices) or args.gang,
            gang_scheduler_name=args.gang_scheduler_name,
            tpu_slices=args.tpu_slices,
            scheduler_policy=args.scheduler_policy,
            tenant_weights=_kv_pairs(args.tenant_weight, float, "--tenant-weight",
                                     minimum=0, exclusive=True),
            tenant_caps=_kv_pairs(args.tenant_cap, int, "--tenant-cap",
                                  minimum=0),
            enable_preemption=not args.disable_preemption,
            enable_elastic=not args.disable_elastic,
            workloads=args.workloads,
            object_storage=args.object_storage,
            event_storage=args.event_storage,
            storage_db_path=args.storage_db_path,
            enable_leader_election=getattr(args, "enable_leader_election", False),
            leader_lease_path=getattr(args, "leader_lease_path", DEFAULT_LEASE_PATH),
            leader_lease_duration=getattr(args, "leader_lease_duration", 15.0),
            leader_renew_period=getattr(args, "leader_renew_period", 5.0),
            leader_retry_period=getattr(args, "leader_retry_period", 2.0),
            journal_dir=getattr(args, "journal_dir", ""),
            journal_compact_bytes=getattr(
                args, "journal_compact_bytes", 1024 * 1024),
            history_dir=getattr(args, "history_dir", ""),
            history_retention_max_age_s=getattr(
                args, "history_retention_age", 0.0),
            history_retention_max_bytes=getattr(
                args, "history_retention_bytes", 0),
            kube_api_url=getattr(args, "kube_api_url", ""),
            kube_namespace=getattr(args, "kube_namespace", "default"),
        )
    )


# ---------------------------------------------------------------------------
# client commands (kubectl-style, against a running `operator` server)
# ---------------------------------------------------------------------------


def _client_request(args, method: str, path: str, body=None):
    import urllib.error
    import urllib.request

    url = args.server.rstrip("/") + path
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    token = args.api_token or os.environ.get("KUBEDL_API_TOKEN", "")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            ctype = r.headers.get("Content-Type", "")
            raw = r.read().decode()
    except urllib.error.HTTPError as e:
        print(f"error: HTTP {e.code}: {e.read().decode()}", file=sys.stderr)
        return None
    except urllib.error.URLError as e:
        print(f"error: cannot reach {url}: {e.reason}", file=sys.stderr)
        return None
    return json.loads(raw) if ctype.startswith("application/json") else raw


def _job_phase(status) -> str:
    """Latest True condition type — the kubectl STATUS column."""
    for c in reversed((status or {}).get("conditions") or []):
        if str(c.get("status", "")).lower() in ("true", "1"):
            return str(c.get("type", "Unknown"))
    return "Pending"


def _format_row(row, widths) -> str:
    return "".join(str(c).ljust(widths[i]) for i, c in enumerate(row)).rstrip()


def _grow_widths(widths, row) -> None:
    """Widen columns for a continuation row longer than anything in the
    initial snapshot, so later rows stay aligned with each other."""
    for i, cell in enumerate(row):
        if i < len(widths):
            widths[i] = max(widths[i], len(str(cell)) + 2)


def _print_table(rows):
    """Print aligned rows; returns the column widths so continuation rows
    (watch mode) can keep the alignment."""
    if not rows:
        return []
    widths = [max(len(str(r[i])) for r in rows) + 2 for i in range(len(rows[0]))]
    for r in rows:
        print(_format_row(r, widths), flush=True)
    return widths


def cmd_get(args) -> int:
    if args.name:
        if getattr(args, "watch", False):
            print("error: -w/--watch applies to the list form "
                  f"(kubedl-tpu get {args.kind} -w)", file=sys.stderr)
            return 2
        obj = _client_request(
            args, "GET", f"/apis/{args.kind}/{args.namespace}/{args.name}"
        )
        if obj is None:
            return 1
        print(json.dumps(obj, indent=2, default=str))
        return 0

    def snapshot():
        listing = _client_request(args, "GET", f"/apis/{args.kind}")
        if listing is None:
            return None
        rows = []
        for item in listing.get("items", []):
            meta = item.get("metadata") or {}
            if not args.all_namespaces and meta.get("namespace") != args.namespace:
                continue
            rows.append((meta.get("namespace", ""), meta.get("name", ""),
                         _job_phase(item.get("status"))))
        return rows

    rows = snapshot()
    if rows is None:
        return 1
    header = ("NAMESPACE", "NAME", "STATUS")
    widths = _print_table([header] + rows)
    if not getattr(args, "watch", False):
        return 0
    # kubectl -w: poll and print rows whose status changed, appeared, or
    # were deleted, keeping the initial table's column alignment; each
    # row flushes so piped output streams. Transient request failures
    # are retried a few times before giving up. KUBEDL_WATCH_MAX bounds
    # the loop for tests; default runs until interrupted.
    seen = dict(((ns, name), st) for ns, name, st in rows)
    max_polls = int(os.environ.get("KUBEDL_WATCH_MAX", "0"))
    polls = failures = 0
    try:
        while not max_polls or polls < max_polls:
            time.sleep(float(os.environ.get("KUBEDL_WATCH_INTERVAL", "2")))
            polls += 1
            rows = snapshot()
            if rows is None:
                failures += 1
                if failures >= 3:
                    print("error: watch lost the server (3 consecutive "
                          "failures)", file=sys.stderr)
                    return 1
                continue
            failures = 0
            current = set()
            for ns, name, st in rows:
                current.add((ns, name))
                if seen.get((ns, name)) != st:
                    seen[(ns, name)] = st
                    _grow_widths(widths, (ns, name, st))
                    print(_format_row((ns, name, st), widths), flush=True)
            for key in sorted(set(seen) - current):
                del seen[key]
                _grow_widths(widths, (key[0], key[1], "Deleted"))
                print(_format_row((key[0], key[1], "Deleted"), widths),
                      flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_apply(args) -> int:
    rc = 0
    for path in args.files:
        for manifest in _load_manifests(path):
            kind = manifest.get("kind", "")
            out = _client_request(args, "POST", f"/apis/{kind}", body=manifest)
            if out is None:
                rc = 1
                continue
            meta = out.get("metadata") or {}
            print(f"applied {kind} {meta.get('namespace')}/{meta.get('name')}")
    return rc


def cmd_delete(args) -> int:
    out = _client_request(
        args, "DELETE", f"/apis/{args.kind}/{args.namespace}/{args.name}"
    )
    if out is None:
        return 1
    print(f"deleted {args.kind} {args.namespace}/{args.name}")
    return 0


def cmd_logs(args) -> int:
    path = f"/logs/{args.namespace}/{args.pod}"
    params = []
    if args.container:
        params.append(f"container={args.container}")
    if args.tail is not None:
        params.append(f"tail={args.tail}")
    if params:
        path += "?" + "&".join(params)
    out = _client_request(args, "GET", path)
    if out is None:
        return 1
    sys.stdout.write(out if isinstance(out, str) else str(out))
    return 0


def cmd_describe(args) -> int:
    """kubectl-describe-style view of one job: metadata, replica specs,
    the condition machine's history, replica statuses, and the job's
    events — the triage view `get` (one JSON blob) doesn't give."""
    obj = _client_request(
        args, "GET", f"/apis/{args.kind}/{args.namespace}/{args.name}")
    if obj is None:
        return 1
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    print(f"Name:      {meta.get('name', '')}")
    print(f"Namespace: {meta.get('namespace', '')}")
    print(f"Kind:      {obj.get('kind', args.kind)}")
    print(f"Created:   {meta.get('creationTimestamp', '')}")
    print(f"Status:    {_job_phase(status)}")
    replica_key = next((k for k in spec if k.endswith("ReplicaSpecs")), None)
    if replica_key:
        print("Replicas:")
        for rtype, rspec in sorted((spec.get(replica_key) or {}).items()):
            rstat = (status.get("replicaStatuses") or {}).get(rtype) or {}
            print(f"  {rtype}: {rspec.get('replicas', 1)} desired | "
                  f"{rstat.get('active', 0)} active, "
                  f"{rstat.get('succeeded', 0)} succeeded, "
                  f"{rstat.get('failed', 0)} failed "
                  f"(restart {rspec.get('restartPolicy', '')})")
    conds = status.get("conditions") or []
    if conds:
        print("Conditions:")
        rows = [("TYPE", "STATUS", "REASON", "LAST TRANSITION", "MESSAGE")]
        for c in conds:
            rows.append((c.get("type", ""), c.get("status", ""),
                         c.get("reason", ""),
                         c.get("lastTransitionTime", ""),
                         c.get("message", "")))
        _print_table(rows)
    listing = _client_request(args, "GET", f"/events/{args.namespace}")
    if listing is not None:
        kind = obj.get("kind") or args.kind
        rows = _event_rows(listing, only_kind=kind, only_name=args.name,
                           with_object=False)
        if len(rows) > 1:
            print("Events:")
            _print_table(rows)
    return 0


def _event_rows(listing, only_kind=None, only_name=None, with_object=True):
    """Shared event-table builder for `events` (all objects) and
    `describe` (one object: kind AND name must match — a same-named
    object of another kind must not pollute the triage view)."""
    header = (("TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE")
              if with_object else ("TYPE", "REASON", "COUNT", "MESSAGE"))
    rows = [header]
    for e in listing.get("items", []):
        inv = e.get("involvedObject") or e.get("involved_object") or {}
        if only_name is not None and inv.get("name") != only_name:
            continue
        if (only_kind is not None
                and (inv.get("kind") or "").lower() != only_kind.lower()):
            continue
        row = [e.get("type", ""), e.get("reason", "")]
        if with_object:
            row.append(f"{inv.get('kind', '')}/{inv.get('name', '')}")
        row += [e.get("count", 1), e.get("message", "")]
        rows.append(tuple(row))
    return rows


def cmd_events(args) -> int:
    listing = _client_request(args, "GET", f"/events/{args.namespace}")
    if listing is None:
        return 1
    _print_table(_event_rows(listing))
    return 0


def cmd_top(args) -> int:
    """kubectl-top-style view of the operator: TPU slice pool utilization
    plus per-controller reconcile health (from /debug/vars)."""
    vars_ = _client_request(args, "GET", "/debug/vars")
    if vars_ is None:
        return 1
    pool = vars_.get("slice_pool")
    if pool:
        print(f"slice pool: {pool['chips_reserved']}/{pool['chips_total']} chips "
              f"reserved ({pool['utilization']:.0%}), "
              f"{pool['slices_reserved']}/{pool['slices_total']} slices")
        rows = [("SLICE", "TYPE", "CHIPS", "RESERVED BY")]
        for s in pool.get("slices", []):
            rows.append((s["name"], s["type"], s.get("chips", ""),
                         s.get("reserved_by") or "-"))
        _print_table(rows)
        print()
    cap = vars_.get("capacity")
    if cap:
        _print_capacity_tenants(cap)
        print()
    gp = vars_.get("goodput")
    if gp and gp.get("jobs"):
        # RL-fleet columns render only when some job has them — the
        # table stays narrow for training/serving-only operators
        has_rl = any(
            (rec.get("buckets") or {}).get(k)
            for rec in gp["jobs"].values()
            for k in ("rollout", "actor_starved", "learner_starved",
                      "weight_sync"))
        header = ["JOB", "GOODPUT", "WALL_S", "STEPS_S", "QUEUE_S", "INIT_S",
                  "CKPT_S", "RESHARD_S", "EVICT_S"]
        if has_rl:
            header += ["ROLLOUT_S", "ASTARVE_S", "LSTARVE_S", "WSYNC_S"]
        rows = [tuple(header + ["OTHER_S"])]
        for job, rec in sorted(gp["jobs"].items()):
            b = rec.get("buckets") or {}
            row = [
                job, f"{rec.get('ratio', 0.0):.0%}",
                f"{rec.get('wall_s', 0.0):.2f}",
                f"{b.get('steps', 0.0):.2f}", f"{b.get('queue_wait', 0.0):.2f}",
                f"{b.get('init_compile', 0.0):.2f}",
                f"{b.get('checkpoint', 0.0):.2f}",
                f"{b.get('reshard', 0.0):.2f}", f"{b.get('eviction', 0.0):.2f}",
            ]
            if has_rl:
                row += [f"{b.get('rollout', 0.0):.2f}",
                        f"{b.get('actor_starved', 0.0):.2f}",
                        f"{b.get('learner_starved', 0.0):.2f}",
                        f"{b.get('weight_sync', 0.0):.2f}"]
            rows.append(tuple(row + [f"{b.get('other', 0.0):.2f}"]))
        _print_table(rows)
        print()
    rl = vars_.get("rl")
    if rl and rl.get("jobs"):
        rows = [("RL_JOB", "QUEUE", "WLAG", "PRODUCED", "CONSUMED",
                 "STALE_DROP", "STEPS", "STEP_MS", "LOSS")]
        for job, rec in sorted(rl["jobs"].items()):
            rows.append((
                job, rec.get("queue_depth", 0), rec.get("weight_lag", 0),
                rec.get("produced", 0), rec.get("consumed", 0),
                rec.get("stale_dropped", 0), rec.get("learn_steps", 0),
                f"{rec.get('learn_step_s', 0.0) * 1e3:.1f}",
                (f"{rec['loss']:.4f}" if "loss" in rec else "-"),
            ))
        _print_table(rows)
        print()
    weights = vars_.get("weights")
    if weights and weights.get("jobs"):
        rows = [("WEIGHTS_JOB", "VERSION", "PUBLISHED", "CHUNKS",
                 "BYTES", "REPARENTS", "PODS_COMMITTED")]
        for job, rec in sorted(weights["jobs"].items()):
            pods = rec.get("pods") or {}
            version = rec.get("published_version", 0)
            committed = sum(1 for v in pods.values() if v >= version)
            rows.append((
                job, version, rec.get("versions_published", 0),
                rec.get("chunks_relayed", 0), rec.get("bytes_total", 0),
                rec.get("reparents", 0),
                f"{committed}/{len(pods)}" if pods else "-",
            ))
        _print_table(rows)
        print()
    steps = vars_.get("steps")
    if steps and steps.get("jobs"):
        rows = [("STEP_JOB", "PODS", "MEDIAN_STEP_MS", "STRAGGLERS",
                 "COMPILES")]
        for job, rec in sorted(steps["jobs"].items()):
            rows.append((
                job, len(rec.get("pods") or {}),
                f"{rec.get('median_step_s', 0.0) * 1e3:.1f}",
                ",".join(rec.get("stragglers") or []) or "-",
                rec.get("compile_events", 0),
            ))
        _print_table(rows)
        print()
    pipe = vars_.get("pipeline")
    if pipe and pipe.get("jobs"):
        rows = [("PIPELINE_JOB", "SCHEDULE", "STAGES", "BUBBLE", "STEPS",
                 "STAGE_STEP_MS")]
        for job, rec in sorted(pipe["jobs"].items()):
            per_stage = " ".join(
                f"{s}:{t * 1e3:.0f}" for s, t in
                # /debug/vars JSON turns the int stage keys into strings;
                # sort numerically or stage 10 renders before stage 2
                sorted((rec.get("stage_step_s") or {}).items(),
                       key=lambda kv: int(kv[0])))
            rows.append((job, rec.get("schedule", ""), rec.get("stages", 0),
                         f"{rec.get('bubble_frac', 0.0):.3f}",
                         rec.get("steps", 0), per_stage or "-"))
        _print_table(rows)
        print()
    rows = [("CONTROLLER", "RECONCILES", "ERRORS", "REQUEUES", "QUEUE", "MEAN_MS")]
    for name, c in sorted((vars_.get("controllers") or {}).items()):
        rows.append((name, c.get("reconciles", 0), c.get("errors", 0),
                     c.get("requeues", 0), c.get("queue_depth", ""),
                     round(c.get("mean_seconds", 0.0) * 1e3, 2)))
    _print_table(rows)
    return 0


def _print_capacity_tenants(cap) -> None:
    print(f"capacity scheduler: policy={cap.get('policy')} "
          f"preemptions={cap.get('preemptions_total', 0)} "
          f"resizes={cap.get('resizes_total', 0)}")
    reshards = cap.get("reshards_total")
    if reshards is not None:
        downtime = cap.get("resize_downtime") or {}
        n = downtime.get("count", 0)
        mean = (downtime.get("sum", 0.0) / n) if n else 0.0
        print(f"live reshards: ok={reshards.get('ok', 0)} "
              f"staged={reshards.get('staged', 0)} "
              f"fallback={reshards.get('fallback', 0)} "
              f"failed={reshards.get('failed', 0)} "
              f"pending={cap.get('reshards_pending', 0)} "
              f"downtime last={downtime.get('last', 0.0):.2f}s "
              f"mean={mean:.2f}s")
    rows = [("TENANT", "WEIGHT", "CHIPS", "FAIR_SHARE", "SHARE", "CAP",
             "CHIP_S", "PREEMPTED")]
    for tenant, t in sorted((cap.get("tenants") or {}).items()):
        cap_chips = t.get("cap_chips")
        rows.append((
            tenant, t.get("weight", 1.0), t.get("chips_in_use", 0),
            t.get("fair_share_chips", 0.0),
            f"{t.get('share', 0.0):.0%}",
            cap_chips if cap_chips is not None else "-",
            t.get("chip_seconds", 0.0), t.get("preemptions", 0),
        ))
    _print_table(rows)


def cmd_queue(args) -> int:
    """Capacity-scheduler view: the gang queue (who runs, who waits, at
    what shape) plus per-tenant quota state — the triage surface for
    "why isn't my job scheduled"."""
    vars_ = _client_request(args, "GET", "/debug/vars")
    if vars_ is None:
        return 1
    cap = vars_.get("capacity")
    if not cap:
        print("capacity scheduler not enabled (start the operator with "
              "--scheduler-policy)", file=sys.stderr)
        return 1
    _print_capacity_tenants(cap)
    print()
    rows = [("GANG", "TENANT", "PRIO", "SHAPE", "STATE", "SLICES",
             "DRAINING", "WAIT_S", "PREEMPTED")]
    for q in cap.get("queue", []):
        rows.append((
            q.get("gang", ""), q.get("tenant", ""), q.get("priority", 0),
            q.get("shape", ""), q.get("state", ""),
            ",".join(q.get("slices") or []) or "-",
            ",".join(q.get("draining") or []) or "-",
            q.get("waiting_seconds", 0.0), q.get("preemptions", 0),
        ))
    _print_table(rows)
    return 0


def cmd_trace(args) -> int:
    """Flight-recorder view of one job (docs/observability.md): the
    merged cross-plane span timeline, the goodput breakdown computed from
    the same spans, and optional Chrome-trace export for Perfetto.
    Reads the operator's /trace endpoint, or a trace dir directly with
    --dir (offline evidence, e.g. a committed bench artifact)."""
    from kubedl_tpu.obs import chrome_trace, goodput, load_spans

    if args.dir:
        spans = load_spans(args.dir)
        gp = goodput(spans)
        trace_ids = gp.get("trace_ids") or []
    else:
        out = _client_request(
            args, "GET", f"/trace/{args.namespace}/{args.job}")
        if out is None:
            return 1
        spans = out.get("spans") or []
        gp = out.get("goodput") or goodput(spans)
        trace_ids = [out.get("trace_id", "")]
    if not spans:
        print(f"no spans recorded for {args.namespace}/{args.job}",
              file=sys.stderr)
        return 1
    if args.chrome_trace:
        with open(args.chrome_trace, "w") as f:
            json.dump(chrome_trace(spans), f)
        print(f"chrome trace ({len(spans)} spans) written to "
              f"{args.chrome_trace} — load in Perfetto / chrome://tracing")
    t0 = gp.get("t0") or min(s.get("ts", 0.0) for s in spans)
    print(f"trace {args.job}: {len(spans)} spans, "
          f"wall {gp.get('wall_s', 0.0):.3f}s, "
          f"trace_id {' '.join(trace_ids) or '?'}")
    rows = [("T+S", "DUR_S", "SERVICE", "SPAN", "DETAIL")]
    for s in spans:
        attrs = s.get("attrs") or {}
        detail = " ".join(
            f"{k}={attrs[k]}" for k in
            ("step", "stage", "cause", "outcome", "shape", "reason", "error")
            if k in attrs)
        rows.append((
            f"{s.get('ts', 0.0) - t0:+.3f}",
            f"{s.get('dur', 0.0):.3f}",
            s.get("service", ""), s.get("name", ""), detail or "-"))
    _print_table(rows)
    print()
    print(f"goodput: {gp.get('ratio', 0.0):.1%} "
          f"(productive step time / wall time)")
    rows = [("BUCKET", "SECONDS", "SHARE")]
    wall = gp.get("wall_s", 0.0) or 1.0
    for bucket, secs in (gp.get("buckets") or {}).items():
        rows.append((bucket, f"{secs:.3f}", f"{secs / wall:.1%}"))
    _print_table(rows)
    return 0


def cmd_history(args) -> int:
    """Fleet history view of one job (docs/ha.md): the last trace
    snapshot + goodput the history store captured, the lifecycle
    markers, and the job/event rows the storage backends persisted —
    still answerable after both the CRD (TTL) and the trace dir are
    gone, which is when `kubedl-tpu trace` starts returning 404."""
    out = _client_request(
        args, "GET", f"/history/{args.namespace}/{args.job}")
    if out is None:
        return 1
    spans = out.get("spans") or []
    gp = out.get("goodput") or {}
    print(f"history {args.namespace}/{args.job}: {len(spans)} spans "
          f"snapshotted, goodput {gp.get('ratio', 0.0):.1%}")
    job = out.get("job_record")
    if job:
        print(f"job record: kind={job.get('kind') or '?'} "
              f"status={job.get('status') or '?'} "
              f"deleted={bool(job.get('deleted'))} "
              f"created={job.get('gmt_created') or '?'} "
              f"finished={job.get('gmt_finished') or '?'}")
    lifecycle = out.get("lifecycle") or []
    if lifecycle:
        rows = [("EVENT", "DETAIL")]
        for rec in lifecycle:
            detail = " ".join(
                f"{k}={rec[k]}" for k in sorted(rec)
                if k not in ("k", "kind", "t", "event"))
            rows.append((rec.get("event", "?"), detail or "-"))
        _print_table(rows)
    events = out.get("events") or []
    if events:
        rows = [("TYPE", "REASON", "COUNT", "MESSAGE")]
        for e in events:
            rows.append((e.get("type", ""), e.get("reason", ""),
                         e.get("count", 1), e.get("message", "")))
        _print_table(rows)
    return 0


def cmd_analyze(args) -> int:
    """Fleet invariant analyzer (docs/static_analysis.md): run the AST
    lint passes + lock-order analysis and print the report — the same
    gate `make lint`/presubmit runs, inspectable like `top`/`trace`."""
    from kubedl_tpu.analysis.__main__ import main as analysis_main

    argv = []
    if args.json:
        argv.append("--json")
    if args.no_tests:
        argv.append("--no-tests")
    if args.show_allowlisted:
        argv.append("--show-allowlisted")
    if args.list_passes:
        argv.append("--list-passes")
    if args.only:
        argv += ["--only", args.only]
    if args.model:
        argv.append("--model")
    if args.root:
        argv += ["--root", args.root]
    return analysis_main(argv)


def cmd_run(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    op.start()
    server = None
    if args.metrics_port:
        server = OperatorHTTPServer(op, port=args.metrics_port)
        port = server.start()
        print(f"serving metrics/API on http://127.0.0.1:{port}")
    rc = 0
    try:
        jobs = [op.apply(m) for p in args.files for m in _load_manifests(p)]
        for job in jobs:
            print(f"applied {job.kind} {job.metadata.namespace}/{job.metadata.name}")
        deadline = time.monotonic() + args.timeout
        pending = {(j.kind, j.metadata.namespace, j.metadata.name) for j in jobs}
        last_report = 0.0
        while pending and time.monotonic() < deadline:
            for key in list(pending):
                kind, ns, name = key
                try:
                    fresh = op.store.get(kind, ns, name)
                except NotFound:
                    print(f"{kind} {ns}/{name}: deleted before completion")
                    pending.discard(key)
                    rc = 1
                    continue
                if is_succeeded(fresh.status):
                    print(f"{kind} {ns}/{name}: Succeeded")
                    pending.discard(key)
                elif is_failed(fresh.status):
                    cond = fresh.status.conditions[-1]
                    print(f"{kind} {ns}/{name}: Failed — {cond.message}")
                    pending.discard(key)
                    rc = 1
            if time.monotonic() - last_report > 5:
                last_report = time.monotonic()
                for kind, ns, name in pending:
                    phases = [
                        (p.metadata.name, p.status.phase.value)
                        for p in op.store.list("Pod", namespace=ns)
                        if p.metadata.labels.get("job-name") == name
                    ]
                    print(f"waiting on {kind} {ns}/{name}: pods={phases}")
            time.sleep(0.1)
        if pending:
            print(f"timed out waiting for: {sorted(pending)}")
            rc = 1
    finally:
        if server:
            server.stop()
        op.stop()
    return rc


def cmd_operator(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    # Construct the server BEFORE op.start(): its token validation can
    # raise (non-loopback bind without a token), and failing here must not
    # leave a leader lease held or manager threads running.
    server = OperatorHTTPServer(
        op, host=args.bind, port=args.metrics_port or 8443,
        token=getattr(args, "api_token", None),
    )
    if args.enable_leader_election:
        print(f"acquiring leadership lease at {args.leader_lease_path} ...")
    op.start()
    if op.elector is not None:
        print(f"elected leader as {op.elector.identity}")
    port = server.start()
    print(f"kubedl-tpu operator serving on http://{args.bind}:{port} "
          f"(kinds: {sorted(op.reconcilers)})")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        op.stop()
    return 0


def cmd_webhook(args) -> int:
    """Serve admission webhooks until interrupted (docs/kubernetes.md)."""
    from kubedl_tpu.k8s.webhook import AdmissionWebhookServer

    srv = AdmissionWebhookServer(
        bind=args.bind, port=args.port,
        certfile=args.tls_cert or None, keyfile=args.tls_key or None,
    ).start()
    scheme = "https" if args.tls_cert else "http"
    print(f"admission webhook on {scheme}://{args.bind}:{srv.port} "
          f"(/validate /mutate /healthz)", flush=True)
    try:
        import signal as _signal

        _signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        srv.stop()
    return 0


def cmd_validate(args) -> int:
    op = _mk_operator(args)
    op.register_all()
    rc = 0
    for path in args.files:
        for m in _load_manifests(path):
            kind = m.get("kind", "")
            canonical = op._kind_by_lower.get(kind.lower())
            if canonical is None:
                print(f"{path}: unknown kind {kind!r}")
                rc = 1
                continue
            engine = op.reconcilers[canonical]
            from kubedl_tpu.utils.serde import from_dict

            job = from_dict(engine.controller.job_type(), m)
            engine.controller.set_defaults(job)
            try:
                api_validate(job, engine.controller)
            except ValidationError as e:
                print(f"{path}: INVALID — {e}")
                rc = 1
                continue
            n = sum(int(s.replicas or 0) for s in engine.controller.replica_specs(job).values())
            print(f"{path}: {canonical} {job.metadata.name} ok ({n} replicas)")
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubedl-tpu")
    parser.add_argument("--max-reconciles", type=int, default=1)
    parser.add_argument("--workloads", default="*")
    parser.add_argument("--gang-scheduler-name", default="tpu-slice")
    parser.add_argument("--gang", action="store_true", help="enable gang scheduling")
    parser.add_argument("--tpu-slices", nargs="*", default=[],
                        help="TPU pool, e.g. v5e-8 v5p-32")
    # capacity scheduler (docs/scheduling.md): tenant fair-share,
    # preemption, elastic resize over the slice pool
    parser.add_argument("--scheduler-policy", default="",
                        choices=["", "fifo", "priority", "fair_share", "gavel"],
                        help="enable the capacity scheduler with this policy")
    parser.add_argument("--tenant-weight", action="append", default=[],
                        metavar="TENANT=WEIGHT",
                        help="fair-share weight (repeatable; default 1.0)")
    parser.add_argument("--tenant-cap", action="append", default=[],
                        metavar="TENANT=CHIPS",
                        help="hard chips-in-use ceiling (repeatable)")
    parser.add_argument("--disable-preemption", action="store_true",
                        help="scheduler never evicts running gangs "
                             "(also disables elastic grow, which evicts)")
    parser.add_argument("--disable-elastic", action="store_true",
                        help="scheduler never resizes gangs across their "
                             "declared tpuSliceFallbacks shapes")
    # persistence flags (ref --object-storage/--event-storage, persist_controller.go:30-74)
    parser.add_argument("--object-storage", default="",
                        help="object history backend name, e.g. sqlite")
    parser.add_argument("--event-storage", default="",
                        help="event history backend name, e.g. sqlite")
    parser.add_argument("--storage-db-path", default=":memory:",
                        help="database path for the sqlite backend")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run job manifests to completion locally")
    p_run.add_argument("-f", "--files", nargs="+", required=True)
    p_run.add_argument("--timeout", type=float, default=600.0)
    p_run.add_argument("--metrics-port", type=int, default=0)
    p_run.set_defaults(fn=cmd_run)

    p_op = sub.add_parser("operator", help="serve the operator over HTTP")
    p_op.add_argument("--bind", default="127.0.0.1")
    p_op.add_argument("--metrics-port", type=int, default=8443)
    # ref main.go:56: leader election defaults ON for the deployed operator
    p_op.add_argument("--enable-leader-election", action=argparse.BooleanOptionalAction,
                      default=True)
    p_op.add_argument("--leader-lease-path", default=DEFAULT_LEASE_PATH)
    # kube mode elects on a coordination.k8s.io Lease; client-go-ish timing
    p_op.add_argument("--leader-lease-duration", type=float, default=15.0)
    p_op.add_argument("--leader-renew-period", type=float, default=5.0)
    p_op.add_argument("--leader-retry-period", type=float, default=2.0)
    p_op.add_argument("--kube-api-url", default="",
                      help="reconcile real cluster objects through this "
                           "kube-apiserver ('in-cluster' = service account)")
    p_op.add_argument("--kube-namespace", default="default")
    p_op.add_argument("--api-token", default=None,
                      help="bearer token for the HTTP API (env KUBEDL_API_TOKEN); "
                           "REQUIRED for non-loopback --bind")
    # durable control plane (docs/ha.md): the deployed operator journals
    # and keeps history by default, under the data root (KUBEDL_DATA_DIR)
    p_op.add_argument("--journal-dir",
                      default=os.path.join(data_root(), "journal"),
                      help="write-ahead grant/drain journal dir "
                           "('' disables)")
    p_op.add_argument("--journal-compact-bytes", type=int,
                      default=1024 * 1024,
                      help="compact the journal (snapshot + truncate) "
                           "once it grows past this many bytes "
                           "(0 disables compaction)")
    p_op.add_argument("--history-dir",
                      default=os.path.join(data_root(), "history"),
                      help="fleet history store dir, outlives job TTL "
                           "('' disables)")
    p_op.add_argument("--history-retention-age", type=float, default=0.0,
                      help="prune history records older than this many "
                           "seconds (0 keeps forever)")
    p_op.add_argument("--history-retention-bytes", type=int, default=0,
                      help="prune oldest history records once the log "
                           "grows past this many bytes (0 = unbounded)")
    p_op.set_defaults(fn=cmd_operator)

    p_val = sub.add_parser("validate", help="parse and default manifests")
    p_val.add_argument("-f", "--files", nargs="+", required=True)
    p_val.set_defaults(fn=cmd_validate)

    p_wh = sub.add_parser(
        "webhook",
        help="serve admission webhooks (/validate + /mutate AdmissionReview)",
    )
    p_wh.add_argument("--bind", default="0.0.0.0")
    p_wh.add_argument("--port", type=int, default=9443)
    p_wh.add_argument("--tls-cert", default="",
                      help="TLS cert path (apiserver requires HTTPS)")
    p_wh.add_argument("--tls-key", default="")
    p_wh.set_defaults(fn=cmd_webhook)

    # kubectl-style client commands against a running `operator` server
    def client_parser(name, help_):
        p = sub.add_parser(name, help=help_)
        p.add_argument("--server", default=os.environ.get(
            "KUBEDL_SERVER", "http://127.0.0.1:8443"))
        p.add_argument("--api-token", default=None,
                       help="bearer token (env KUBEDL_API_TOKEN)")
        p.add_argument("-n", "--namespace", default="default")
        return p

    p_get = client_parser("get", "list jobs of a kind, or show one as JSON")
    p_get.add_argument("kind")
    p_get.add_argument("name", nargs="?", default="")
    p_get.add_argument("-A", "--all-namespaces", action="store_true")
    p_get.add_argument("-w", "--watch", action="store_true",
                       help="poll and print status changes until interrupted")
    p_get.set_defaults(fn=cmd_get)

    p_apply = client_parser("apply", "submit manifests to the operator")
    p_apply.add_argument("-f", "--files", nargs="+", required=True)
    p_apply.set_defaults(fn=cmd_apply)

    p_del = client_parser("delete", "delete a job")
    p_del.add_argument("kind")
    p_del.add_argument("name")
    p_del.set_defaults(fn=cmd_delete)

    p_logs = client_parser("logs", "print a pod's container logs")
    p_logs.add_argument("pod")
    p_logs.add_argument("-c", "--container", default="")
    p_logs.add_argument("--tail", type=int, default=None)
    p_logs.set_defaults(fn=cmd_logs)

    p_desc = client_parser(
        "describe", "conditions, replica statuses, and events for one job")
    p_desc.add_argument("kind")
    p_desc.add_argument("name")
    p_desc.set_defaults(fn=cmd_describe)

    p_ev = client_parser("events", "list events in a namespace")
    p_ev.set_defaults(fn=cmd_events)

    p_top = client_parser("top", "slice-pool utilization + controller health")
    p_top.set_defaults(fn=cmd_top)

    p_queue = client_parser(
        "queue", "capacity-scheduler gang queue + tenant quota state")
    p_queue.set_defaults(fn=cmd_queue)

    p_trace = client_parser(
        "trace", "flight-recorder span timeline + goodput for one job")
    p_trace.add_argument("job")
    p_trace.add_argument("--chrome-trace", default="", metavar="OUT.json",
                         help="also export Chrome trace JSON (Perfetto)")
    p_trace.add_argument("--dir", default="",
                         help="read spans from a local trace dir instead "
                              "of the operator server")
    p_trace.set_defaults(fn=cmd_trace)

    p_hist = client_parser(
        "history", "fleet history for one job — outlives job TTL "
                   "(docs/ha.md)")
    p_hist.add_argument("job")
    p_hist.set_defaults(fn=cmd_history)

    p_an = sub.add_parser(
        "analyze",
        help="fleet invariant analyzer: AST lint passes + lock-order "
             "report (docs/static_analysis.md)")
    p_an.add_argument("--json", action="store_true",
                      help="machine-readable report")
    p_an.add_argument("--no-tests", action="store_true",
                      help="skip tests/ (default scope includes it)")
    p_an.add_argument("--show-allowlisted", action="store_true",
                      help="also print pragma-suppressed findings")
    p_an.add_argument("--only", default="",
                      help="comma-separated pass ids to run")
    p_an.add_argument("--list-passes", action="store_true",
                      help="print the registered pass ids and exit")
    p_an.add_argument("--model", action="store_true",
                      help="also run the protocol model checker "
                           "(exhaustive grant/drain/resize exploration)")
    p_an.add_argument("--root", default="",
                      help="repo root (default: auto-detect)")
    p_an.set_defaults(fn=cmd_analyze)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
