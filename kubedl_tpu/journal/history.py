"""Fleet history store — evidence that outlives job TTL (docs/ha.md).

The flight recorder's spans and goodput summaries die with the trace
dir, and the CRD dies with the TTL — after that, nothing can answer
"what happened to yesterday's job".  The reference treats durable
history as core (persist controllers + pluggable storage backends);
this module closes the same gap for the evidence planes:

* :class:`HistoryStore` keeps an append-only ``history.jsonl`` under
  the operator's data root (same torn-tail-tolerant JSONL idiom as the
  grant journal and ``storage/jsonl_backend.py``) holding per-job
  trace-span snapshots + goodput summaries + lifecycle markers, and
  answers queries by joining that file with the job/event rows the
  existing ``storage/`` backends already persist;
* :class:`HistoryPersistController` watches every workload kind and
  snapshots the job's trace dir into the store when the job reaches a
  terminal condition AND when the object disappears (TTL / deletion —
  the last chance before the trace dir is garbage-collected).

Queryable through ``GET /history/<ns>/<job>`` (server.py) and
``kubedl-tpu history`` (cli.py) after both the CRD and the trace dir
are gone.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from kubedl_tpu.core.manager import Result
from kubedl_tpu.core.store import NotFound
from kubedl_tpu.storage.interface import Query

log = logging.getLogger(__name__)

__all__ = ["HistoryStore", "HistoryPersistController",
           "setup_history_controllers"]


class HistoryStore:
    """Append-only per-job history records + backend row joins."""

    def __init__(
        self,
        root_dir: str,
        object_backend=None,
        event_backend=None,
        region: str = "",
        retention_max_age_s: float = 0.0,
        retention_max_bytes: int = 0,
    ) -> None:
        self.root_dir = root_dir
        self.path = os.path.join(root_dir, "history.jsonl")
        self.object_backend = object_backend
        self.event_backend = event_backend
        self.region = region
        # retention bounds (0 = unbounded): records older than max-age
        # are dropped, and when the file grows past max-bytes the
        # oldest records are dropped until it fits — both via a
        # tmp+replace rewrite stamped with a prune-epoch marker
        self.retention_max_age_s = float(retention_max_age_s)
        self.retention_max_bytes = int(retention_max_bytes)
        self.prune_epoch = 0
        self.pruned_records = 0
        self._lock = threading.RLock()
        self._fh = None
        # key -> latest trace record (replayed at initialize; queries
        # never rescan the file)
        self._latest: Dict[str, Dict] = {}
        # key -> lifecycle markers, in append order
        self._lifecycle: Dict[str, List[Dict]] = {}

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    def initialize(self) -> None:
        """Replay the existing file (skipping torn lines) into the
        in-memory indexes, then open the append handle — the
        ``storage/jsonl_backend.py`` idiom."""
        with self._lock:
            if self._fh is not None:
                return
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue  # torn tail / corrupt line
                        if isinstance(rec, dict) and rec.get("k"):
                            self._index(rec)
                        elif (isinstance(rec, dict)
                                and rec.get("kind") == "prune"):
                            # keyless epoch stamp from an earlier prune:
                            # carry the epoch forward, never index it
                            self.prune_epoch = max(
                                self.prune_epoch,
                                int(rec.get("epoch", 0)))
            except OSError:
                pass  # cold start
            os.makedirs(self.root_dir, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._maybe_prune()

    def _index(self, rec: Dict) -> None:
        key = rec["k"]
        if rec.get("kind") == "trace":
            self._latest[key] = rec
        else:
            self._lifecycle.setdefault(key, []).append(rec)

    def _append(self, rec: Dict) -> None:
        with self._lock:
            if self._fh is None:
                self.initialize()
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._index(rec)
            if (self.retention_max_bytes
                    and self._fh.tell() > self.retention_max_bytes):
                self._maybe_prune()

    # -- retention ---------------------------------------------------------

    def _records_newest_last(self) -> List[Dict]:
        with self._lock:
            recs = list(self._latest.values())
            for markers in self._lifecycle.values():
                recs.extend(markers)
        recs.sort(key=lambda r: r.get("t", 0.0))
        return recs

    def _maybe_prune(self) -> int:
        """Apply the retention bounds, if any are set and exceeded."""
        if not (self.retention_max_age_s or self.retention_max_bytes):
            return 0
        return self.prune()

    def prune(self, now: Optional[float] = None) -> int:
        """Rewrite history.jsonl down to the retention bounds; returns
        the number of records dropped.  The rewrite is tmp+os.replace
        (a crash mid-prune leaves the old complete file), leads with a
        keyless epoch-stamped prune marker (replay skips it — only the
        epoch is carried), and the in-memory indexes are rebuilt from
        the kept set so replay-after-prune and the live store agree."""
        now = time.time() if now is None else now
        with self._lock:
            if self._fh is None:
                self.initialize()
            recs = self._records_newest_last()
            n_before = len(recs)
            kept = list(recs)
            if self.retention_max_age_s:
                cutoff = now - self.retention_max_age_s
                kept = [r for r in kept if r.get("t", 0.0) >= cutoff]
            lines = [json.dumps(r, sort_keys=True) + "\n" for r in kept]
            if self.retention_max_bytes:
                size = sum(len(ln.encode("utf-8")) for ln in lines)
                while lines and size > self.retention_max_bytes:
                    size -= len(lines[0].encode("utf-8"))
                    lines.pop(0)
                    kept.pop(0)
            dropped = n_before - len(kept)
            if dropped == 0:
                return 0
            self.prune_epoch += 1
            self.pruned_records += dropped
            marker = json.dumps({
                "kind": "prune", "t": now, "epoch": self.prune_epoch,
                "dropped": dropped,
            }, sort_keys=True) + "\n"
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(marker)
                f.writelines(lines)
                f.flush()
                os.fsync(f.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._latest.clear()
            self._lifecycle.clear()
            for r in kept:
                self._index(r)
            log.info("history: pruned %d record(s) (epoch %d, %d kept)",
                     dropped, self.prune_epoch, len(kept))
            return dropped

    # -- writers (HistoryPersistController) -------------------------------

    def record_spans(self, namespace: str, name: str,
                     spans: List[Dict], goodput: Dict) -> None:
        """Snapshot a job's whole trace timeline + goodput summary."""
        self._append({
            "k": self._key(namespace, name),
            "kind": "trace",
            "t": time.time(),
            "spans": spans,
            "goodput": goodput,
        })

    def record_lifecycle(self, namespace: str, name: str,
                         event: str, **attrs) -> None:
        rec = {"k": self._key(namespace, name), "kind": "lifecycle",
               "t": time.time(), "event": event}
        rec.update(attrs)
        self._append(rec)

    # -- queries (server /history, kubedl-tpu history) ---------------------

    def span_count(self, namespace: str, name: str) -> int:
        with self._lock:
            rec = self._latest.get(self._key(namespace, name))
            return len(rec.get("spans", [])) if rec else 0

    def get(self, namespace: str, name: str) -> Optional[Dict]:
        """Everything history knows about one job, or None: the latest
        trace snapshot + lifecycle markers from history.jsonl, joined
        with the job row and events the storage backends persisted
        (deleted rows included — outliving TTL is the point)."""
        key = self._key(namespace, name)
        with self._lock:
            trace = self._latest.get(key)
            lifecycle = list(self._lifecycle.get(key, []))
        job_row = None
        events: List[Dict] = []
        if self.object_backend is not None:
            try:
                rows = self.object_backend.list_jobs(Query(
                    name=name, namespace=namespace, region=self.region))
                if rows:
                    r = rows[0]  # newest first (backend sort order)
                    job_row = {
                        "kind": r.kind, "job_id": r.job_id,
                        "status": r.status, "deleted": r.deleted,
                        "resources": r.resources,
                        "tenant": r.tenant,
                        "gmt_created": r.gmt_created,
                        "gmt_finished": r.gmt_finished,
                    }
            except Exception:  # noqa: BLE001 — backend racing shutdown
                log.warning("history: job-row query failed for %s", key)
        if self.event_backend is not None:
            try:
                events = [
                    {"reason": e.reason, "message": e.message,
                     "type": e.type, "count": e.count,
                     "last_timestamp": e.last_timestamp}
                    for e in self.event_backend.list_events(
                        namespace, name)
                ]
            except Exception:  # noqa: BLE001 — backend racing shutdown
                log.warning("history: event query failed for %s", key)
        if trace is None and job_row is None and not lifecycle:
            return None
        return {
            "namespace": namespace,
            "job": name,
            "spans": (trace or {}).get("spans", []),
            "goodput": (trace or {}).get("goodput", {}),
            "snapshot_time": (trace or {}).get("t"),
            "lifecycle": lifecycle,
            "job_record": job_row,
            "events": events,
        }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


class HistoryPersistController:
    """Snapshot each job's flight-recorder evidence into the
    HistoryStore at the moments that matter: terminal condition (the
    timeline is complete) and object deletion (TTL fired — last chance
    before the trace dir is garbage-collected).  Mirrors the
    JobPersistController wiring: one instance per workload kind, an
    ordinary ControllerRunner on the shared manager."""

    def __init__(self, controller, history: HistoryStore, store,
                 trace_root: str) -> None:
        self.controller = controller
        self.history = history
        self.store = store
        self.trace_root = trace_root
        self.runner = None

    def setup(self, runner) -> None:
        self.runner = runner
        runner.watch(self.controller.kind, self._on_event)

    def _on_event(self, event) -> None:
        obj = event.obj
        self.runner.enqueue(
            f"{obj.metadata.namespace}/{obj.metadata.name}/"
            f"{obj.metadata.uid}")

    def _snapshot(self, namespace: str, name: str) -> None:
        """Idempotent-ish: re-snapshot only when the timeline grew (the
        trace dir keeps filling between terminal condition and TTL)."""
        from kubedl_tpu.obs import goodput as compute_goodput
        from kubedl_tpu.obs import job_trace_dir, load_spans

        d = job_trace_dir(self.trace_root, namespace, name) \
            if self.trace_root else ""
        if not d or not os.path.isdir(d):
            return
        spans = load_spans(d)
        if not spans:
            return
        if len(spans) == self.history.span_count(namespace, name):
            return  # nothing new since the last snapshot
        self.history.record_spans(
            namespace, name, spans, compute_goodput(spans))

    def reconcile(self, key: str) -> Result:
        ns, name, uid = key.split("/", 2)
        from kubedl_tpu.api.common import is_failed, is_succeeded

        try:
            job = self.store.get(self.controller.kind, ns, name)
            if job.metadata.uid != uid:
                raise NotFound(key)  # name reused — old job is gone
        except NotFound:
            # TTL / deletion: snapshot whatever the trace dir still
            # holds, then mark the lifecycle so the record says WHY
            # the live object is gone
            self._snapshot(ns, name)
            self.history.record_lifecycle(ns, name, "deleted", uid=uid)
            return Result()
        status = self.controller.job_status(job)
        if is_succeeded(status) or is_failed(status):
            self._snapshot(ns, name)
        return Result()


def setup_history_controllers(
    manager,
    store,
    workload_controllers: Dict[str, object],
    history: HistoryStore,
    trace_root: str,
) -> list:
    """Wire one history controller per workload kind onto the manager
    (the setup_persist_controllers pattern)."""
    created = []
    for kind, wc in workload_controllers.items():
        hpc = HistoryPersistController(wc, history, store, trace_root)
        runner = manager.add_controller(
            f"{kind.lower()}-history", hpc.reconcile)
        hpc.setup(runner)
        created.append(hpc)
    return created
