"""Durable control plane (docs/ha.md).

Three pillars, one package:

* :mod:`kubedl_tpu.journal.wal` — the write-ahead grant/drain journal
  the admitter appends to BEFORE every in-memory commit, and replays
  on restart (flips the pinned restart counterexample in
  ``tests/test_protocol_model.py`` to a proof);
* fencing epochs (:class:`~kubedl_tpu.journal.wal.StaleEpochError`) —
  a deposed-but-still-running old leader's journal appends and
  transport control posts are refused loudly;
* :mod:`kubedl_tpu.journal.history` — the fleet history store that
  outlives job TTL: trace spans, goodput summaries, and job lifecycle
  records queryable via ``GET /history/<ns>/<job>`` and
  ``kubedl-tpu history`` after the CRD and trace dir are gone.
"""
from kubedl_tpu.journal.wal import (
    ENV_JOURNAL_TEST_DELAY,
    GrantJournal,
    JournalError,
    StaleEpochError,
)
from kubedl_tpu.journal.history import HistoryStore

__all__ = [
    "ENV_JOURNAL_TEST_DELAY",
    "GrantJournal",
    "JournalError",
    "StaleEpochError",
    "HistoryStore",
]
