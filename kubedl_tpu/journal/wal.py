"""Write-ahead grant/drain journal (docs/ha.md).

Every admitter state transition the protocol model names (grant,
pods_start, evict-with-shield, release, confirm_drain, drain_timeout,
slice_failed, delete_gang — the RESIZE grow pre-grant rides the evict
record's ``grow`` field) is appended here as an fsync'd, sha-checked
JSONL record *before* the in-memory commit.  On restart the admitter
replays the journal against the observed pod set
(``TPUSliceAdmitter.restore_from_journal``) instead of starting empty;
``analysis/protocol.py``'s journaled-restart machine proves the replay
keeps no-regrant-over-live-pod over the exhaustive 2/3-gang spaces.

Durability contract (mirrors ``storage/jsonl_backend.py``):

* append-only, one JSON object per line, ``open(path, "a")`` +
  ``flush`` + ``fsync`` per record — a record is either fully on disk
  or absent;
* each record carries a sha over its canonical (sorted-keys) JSON;
  replay stops at the first torn or sha-mismatched line, so a crash
  mid-append loses at most the record being written — which by the
  write-AHEAD ordering had not been committed to memory either;
* each record carries the writer's fencing epoch.  ``append`` checks
  the epoch authority (the lease sidecar file,
  ``core.leader.read_epoch``) and raises :class:`StaleEpochError` when
  a newer leader exists — a deposed operator cannot extend the
  journal.

Crash seam for the chaos lane: ``KUBEDL_JOURNAL_TEST_DELAY_S`` sleeps
INSIDE ``append`` after the fsync, widening the window between the
durable record and the in-memory commit so tests/test_journal_chaos.py
can SIGKILL the operator inside it deterministically.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = [
    "ENV_JOURNAL_TEST_DELAY",
    "JOURNAL_VERSION",
    "GrantJournal",
    "JournalError",
    "StaleEpochError",
]

ENV_JOURNAL_TEST_DELAY = "KUBEDL_JOURNAL_TEST_DELAY_S"
JOURNAL_VERSION = 1

#: every op the admitter journals — replay refuses records outside
#: this set (schema drift must be explicit, not silently ignored).
JOURNAL_OPS = frozenset((
    "grant", "pods_start", "evict", "release", "confirm_drain",
    "drain_timeout", "slice_failed", "delete_gang",
))


class JournalError(RuntimeError):
    """Structural journal failure (unknown op, closed journal)."""


class StaleEpochError(JournalError):
    """The epoch authority shows a newer leader: this writer has been
    deposed and must stop — its append was refused."""


def _sha(record: Dict[str, Any]) -> str:
    body = {k: v for k, v in record.items() if k != "sha"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class GrantJournal:
    """One append-only journal file, one writer at a time (the fencing
    epoch enforces the "one" part across processes; the internal lock
    serializes threads of the same operator)."""

    def __init__(
        self,
        path: str,
        epoch: int = 0,
        epoch_authority: Optional[Callable[[], int]] = None,
    ) -> None:
        self.path = path
        self.epoch = int(epoch)
        # callable returning the current fleet-wide epoch (the lease
        # sidecar); None disables fencing (tests, journal-off bench).
        self._authority = epoch_authority
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0
        # counters surfaced by metrics (kubedl_journal_* family)
        self.appends_total = 0
        self.replay_records = 0
        self.replay_conflicts = 0
        self.stale_epoch_refusals = 0

    # -- open / replay ----------------------------------------------------

    def open(self) -> List[Dict[str, Any]]:
        """Scan the existing file (if any), returning every valid
        record in order; stop at the first torn or sha-mismatched line
        (crash tail).  Then open the append handle.  Idempotent."""
        with self._lock:
            if self._fh is not None:
                return []
            records: List[Dict[str, Any]] = []
            torn = 0
            max_epoch = 0
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            torn += 1
                            break
                        if (not isinstance(rec, dict)
                                or rec.get("sha") != _sha(rec)
                                or rec.get("op") not in JOURNAL_OPS):
                            torn += 1
                            break
                        records.append(rec)
                        max_epoch = max(max_epoch, int(rec.get("epoch", 0)))
            except OSError:
                pass  # no journal yet: cold start
            if torn:
                log.warning(
                    "journal %s: stopped replay at torn/corrupt tail "
                    "after %d valid records", self.path, len(records))
            if self.epoch and max_epoch > self.epoch:
                # a newer leader already wrote here; we were deposed
                # before we even started
                self.stale_epoch_refusals += 1
                raise StaleEpochError(
                    f"journal {self.path} holds epoch {max_epoch} > "
                    f"ours {self.epoch}: refusing to open for append")
            self._seq = int(records[-1]["seq"]) if records else 0
            self.replay_records = len(records)
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
            return records

    # -- the write-ahead append -------------------------------------------

    def append(self, op: str, gang: str = "", **data: Any) -> Dict[str, Any]:
        """Durably append one record and return it.  Called by the
        admitter UNDER its own lock, immediately BEFORE the in-memory
        commit — the record must be on disk before memory changes."""
        if op not in JOURNAL_OPS:
            raise JournalError(f"unknown journal op {op!r}")
        with self._lock:
            if self._fh is None:
                raise JournalError(
                    f"journal {self.path} not open (call open() first)")
            if self._authority is not None:
                current = self._authority()
                if current > self.epoch:
                    self.stale_epoch_refusals += 1
                    log.error(
                        "journal %s: APPEND REFUSED — fencing epoch %d "
                        "superseded by %d (a newer leader holds the "
                        "lease); this operator must stop",
                        self.path, self.epoch, current)
                    raise StaleEpochError(
                        f"append refused: epoch {self.epoch} superseded "
                        f"by {current}")
            self._seq += 1
            rec: Dict[str, Any] = {
                "v": JOURNAL_VERSION,
                "seq": self._seq,
                "epoch": self.epoch,
                "t": time.time(),
                "op": op,
                "gang": gang,
                "data": data,
            }
            rec["sha"] = _sha(rec)
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appends_total += 1
        # crash seam (chaos lane): widen the window between the durable
        # append and the caller's in-memory commit.  Outside the lock so
        # a SIGKILL here never leaves lock state behind in-process.
        delay = float(os.environ.get(ENV_JOURNAL_TEST_DELAY, "0") or 0)
        if delay > 0:
            time.sleep(delay)
        return rec

    # -- bookkeeping -------------------------------------------------------

    def note_replay(self, records: int, conflicts: int) -> None:
        """Recorded by the admitter after restore_from_journal."""
        with self._lock:
            self.replay_records = records
            self.replay_conflicts = conflicts

    def snapshot(self) -> Dict[str, int]:
        """Metrics snapshot (kubedl_journal_* family)."""
        with self._lock:
            return {
                "appends_total": self.appends_total,
                "replay_records_total": self.replay_records,
                "replay_conflicts_total": self.replay_conflicts,
                "stale_epoch_refusals_total": self.stale_epoch_refusals,
                "epoch": self.epoch,
                "seq": self._seq,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
