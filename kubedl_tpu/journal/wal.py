"""Write-ahead grant/drain journal (docs/ha.md).

Every admitter state transition the protocol model names (grant,
pods_start, evict-with-shield, release, confirm_drain, drain_timeout,
slice_failed, delete_gang — the RESIZE grow pre-grant rides the evict
record's ``grow`` field) is appended here as an fsync'd, sha-checked
JSONL record *before* the in-memory commit.  On restart the admitter
replays the journal against the observed pod set
(``TPUSliceAdmitter.restore_from_journal``) instead of starting empty;
``analysis/protocol.py``'s journaled-restart machine proves the replay
keeps no-regrant-over-live-pod over the exhaustive 2/3-gang spaces.

Durability contract (mirrors ``storage/jsonl_backend.py``):

* append-only, one JSON object per line; a record is written + flushed
  to the OS under the journal lock (so log order == commit order) and
  fsync-covered before any effect of its transition ESCAPES the
  admitter — a placement returned to a caller, a pod started, an
  eviction delivered.  A record is either fully on disk or absent;
* each record carries a sha over its canonical (sorted-keys) JSON;
  replay stops at the first torn or sha-mismatched line, so a crash
  mid-append loses at most the record being written — which by the
  write-AHEAD ordering had not externalized any effect either;
* each record carries the writer's fencing epoch.  Appends check the
  epoch authority (the lease sidecar file, ``core.leader.read_epoch``)
  and raise :class:`StaleEpochError` when a newer leader exists — a
  deposed operator cannot extend (or compact) the journal.

Group commit (docs/control_plane_scale.md): ``append_nosync`` does the
epoch check + write + flush and returns a sequence ticket; ``sync_to``
is a leader/follower group fsync — the first waiter becomes the leader
and issues ONE fsync covering every record written so far; followers
whose tickets that fsync covers return without touching the disk.  A
caller's append is never considered committed before a sync covers it:
the admitter syncs before any entry point returns.  ``append`` (=
``append_nosync`` + ``sync_to``) keeps the original blocking,
single-writer behavior — same syscall sequence, same latency.  Group
commit changes batching only, never ordering (the journal lock
serializes writes) or the commit point (the fsync covering the record).

Compaction (``compact``): snapshots effective state into a fresh
epoch-stamped file via tmp + ``os.replace`` and truncates the history.
Sequence numbers stay MONOTONIC across a compaction (the snapshot is
re-stamped above the current watermark) so outstanding sync tickets are
always covered, never orphaned.

Crash seam for the chaos lane: ``KUBEDL_JOURNAL_TEST_DELAY_S`` makes
every append eagerly fsync and then sleep AFTER the fsync, widening the
window between the durable record and the in-memory commit so
tests/test_journal_chaos.py can SIGKILL the operator inside it
deterministically.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from kubedl_tpu.analysis.witness import new_lock, new_rlock

log = logging.getLogger(__name__)

__all__ = [
    "ENV_JOURNAL_TEST_DELAY",
    "JOURNAL_VERSION",
    "GrantJournal",
    "JournalError",
    "StaleEpochError",
]

ENV_JOURNAL_TEST_DELAY = "KUBEDL_JOURNAL_TEST_DELAY_S"
JOURNAL_VERSION = 1

#: every op the admitter journals — replay refuses records outside
#: this set (schema drift must be explicit, not silently ignored).
JOURNAL_OPS = frozenset((
    "grant", "pods_start", "evict", "release", "confirm_drain",
    "drain_timeout", "slice_failed", "delete_gang",
))


class JournalError(RuntimeError):
    """Structural journal failure (unknown op, closed journal)."""


class StaleEpochError(JournalError):
    """The epoch authority shows a newer leader: this writer has been
    deposed and must stop — its append was refused."""


def _sha(record: Dict[str, Any]) -> str:
    body = {k: v for k, v in record.items() if k != "sha"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class GrantJournal:
    """One append-only journal file, one writer at a time (the fencing
    epoch enforces the "one" part across processes; the internal lock
    serializes threads of the same operator).

    Lock order (one-directional, witness-named): ``_sync_mutex`` ->
    ``_lock`` -> ``_sync_cond``'s lock.  ``append_nosync`` takes only
    ``_lock``; the group-commit leader takes ``_sync_mutex`` alone
    around the fsync (so writers keep writing while the disk syncs) and
    captures the watermark under ``_lock`` briefly; ``compact``/``close``
    take ``_sync_mutex`` -> ``_lock`` to quiesce the disk."""

    def __init__(
        self,
        path: str,
        epoch: int = 0,
        epoch_authority: Optional[Callable[[], int]] = None,
        compact_bytes: int = 0,
    ) -> None:
        self.path = path
        self.epoch = int(epoch)
        # callable returning the current fleet-wide epoch (the lease
        # sidecar); None disables fencing (tests, journal-off bench).
        self._authority = epoch_authority
        # journal size (bytes) past which should_compact() fires;
        # 0 disables compaction.
        self.compact_bytes = int(compact_bytes)
        self._lock = new_rlock("journal.wal.GrantJournal._lock")
        self._fh = None
        self._seq = 0
        # group commit state: _durable_seq is the highest seq an fsync
        # has covered; _sync_leader marks an fsync in flight.
        self._sync_mutex = new_lock("journal.wal.GrantJournal._sync_mutex")
        self._sync_cond = threading.Condition(
            new_lock("journal.wal.GrantJournal._sync_cond"))
        self._durable_seq = 0
        self._sync_leader = False
        # counters surfaced by metrics (kubedl_journal_* family)
        self.appends_total = 0
        self.fsyncs_total = 0
        self.compactions_total = 0
        self.replay_records = 0
        self.replay_conflicts = 0
        self.stale_epoch_refusals = 0

    # -- open / replay ----------------------------------------------------

    def open(self) -> List[Dict[str, Any]]:
        """Scan the existing file (if any), returning every valid
        record in order; stop at the first torn or sha-mismatched line
        (crash tail).  Then open the append handle.  Idempotent."""
        with self._lock:
            if self._fh is not None:
                return []
            records: List[Dict[str, Any]] = []
            torn = 0
            max_epoch = 0
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            torn += 1
                            break
                        if (not isinstance(rec, dict)
                                or rec.get("sha") != _sha(rec)
                                or rec.get("op") not in JOURNAL_OPS):
                            torn += 1
                            break
                        records.append(rec)
                        max_epoch = max(max_epoch, int(rec.get("epoch", 0)))
            except OSError:
                pass  # no journal yet: cold start
            if torn:
                log.warning(
                    "journal %s: stopped replay at torn/corrupt tail "
                    "after %d valid records", self.path, len(records))
            if self.epoch and max_epoch > self.epoch:
                # a newer leader already wrote here; we were deposed
                # before we even started
                self.stale_epoch_refusals += 1
                raise StaleEpochError(
                    f"journal {self.path} holds epoch {max_epoch} > "
                    f"ours {self.epoch}: refusing to open for append")
            self._seq = int(records[-1]["seq"]) if records else 0
            self.replay_records = len(records)
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
            seq = self._seq
        with self._sync_cond:
            # everything replayed is on disk already
            self._durable_seq = max(self._durable_seq, seq)
        return records

    # -- the write-ahead append -------------------------------------------

    def append(self, op: str, gang: str = "", **data: Any) -> Dict[str, Any]:
        """Durably append one record and return it: write + flush under
        the lock, then block until a group fsync covers it.  A single
        writer becomes the sync leader immediately — same syscall
        sequence and latency as the original per-record fsync."""
        rec = self.append_nosync(op, gang, **data)
        self.sync_to(int(rec["seq"]))
        return rec

    def append_nosync(self, op: str, gang: str = "", **data: Any) -> Dict[str, Any]:
        """Write + flush one record and return it WITHOUT waiting for an
        fsync.  Called by the admitter UNDER its own lock, immediately
        BEFORE the in-memory commit, so journal order always equals
        commit order.  The caller must ``sync_to`` the returned seq (the
        admitter's per-entry-point sync barrier) before any effect of
        the transition escapes the process."""
        if op not in JOURNAL_OPS:
            raise JournalError(f"unknown journal op {op!r}")
        with self._lock:
            if self._fh is None:
                raise JournalError(
                    f"journal {self.path} not open (call open() first)")
            if self._authority is not None:
                current = self._authority()
                if current > self.epoch:
                    self.stale_epoch_refusals += 1
                    log.error(
                        "journal %s: APPEND REFUSED — fencing epoch %d "
                        "superseded by %d (a newer leader holds the "
                        "lease); this operator must stop",
                        self.path, self.epoch, current)
                    raise StaleEpochError(
                        f"append refused: epoch {self.epoch} superseded "
                        f"by {current}")
            self._seq += 1
            rec: Dict[str, Any] = {
                "v": JOURNAL_VERSION,
                "seq": self._seq,
                "epoch": self.epoch,
                "t": time.time(),
                "op": op,
                "gang": gang,
                "data": data,
            }
            # one serialization per record, not two: the sha covers the
            # compact sorted body, and the written line is that same
            # body with the sha spliced in before the closing brace.
            # Key order in the file is irrelevant — replay re-parses the
            # line and re-derives the sha from the dict. This runs under
            # the admitter's lock on every grant, so the duplicate
            # json.dumps was a measurable slice of concurrent grant cost
            # (the fleet_scale bench's journal_concurrent lane).
            body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
            sha = hashlib.sha256(body.encode("utf-8")).hexdigest()[:16]
            rec["sha"] = sha
            self._fh.write(body[:-1] + ',"sha":"' + sha + '"}\n')
            self._fh.flush()
            self.appends_total += 1
        # crash seam (chaos lane): make the record durable NOW, then
        # widen the window between the durable append and the caller's
        # in-memory commit.  Outside the lock so a SIGKILL here never
        # leaves lock state behind in-process.
        delay = float(os.environ.get(ENV_JOURNAL_TEST_DELAY, "0") or 0)
        if delay > 0:
            self.sync_to(int(rec["seq"]))
            time.sleep(delay)
        return rec

    def sync_to(self, seq: int) -> None:
        """Block until an fsync covers record `seq` (leader/follower
        group commit).  The first waiter becomes the leader, issues one
        fsync for everything written so far, and wakes every follower
        that fsync covered; a follower whose record is already covered
        returns immediately without touching the disk."""
        if seq <= 0:
            return
        while True:
            with self._sync_cond:
                if self._durable_seq >= seq:
                    return
                if self._sync_leader:
                    # a sync is in flight; it may or may not cover us —
                    # re-check when it lands
                    self._sync_cond.wait(0.5)
                    continue
                self._sync_leader = True
            target = 0
            try:
                with self._sync_mutex:
                    with self._lock:
                        fh = self._fh
                        target = self._seq
                    if fh is not None:
                        # fsync holding only the sync mutex: writers keep
                        # appending while the disk syncs
                        os.fsync(fh.fileno())
                        self.fsyncs_total += 1
                    # fh None: close()/compact() already fsync'd
                    # everything written — target is durable
            finally:
                with self._sync_cond:
                    self._sync_leader = False
                    if target > self._durable_seq:
                        self._durable_seq = target
                    self._sync_cond.notify_all()

    # -- compaction --------------------------------------------------------

    def should_compact(self) -> bool:
        """Size-threshold trigger; the admitter polls this at its
        scheduling choke point and feeds ``compact`` a state snapshot."""
        if self.compact_bytes <= 0:
            return False
        with self._lock:
            if self._fh is None:
                return False
            try:
                return os.fstat(self._fh.fileno()).st_size >= self.compact_bytes
            except OSError:
                return False

    def compact(self, records: Iterable[Tuple[str, str, Dict[str, Any]]]) -> int:
        """Replace the journal's history with an effective-state snapshot:
        `records` is (op, gang, data) tuples replay-equivalent to the
        current in-memory state (the admitter builds them under ITS lock,
        atomically with calling this).  Written to `path + ".tmp"`,
        fsync'd, then ``os.replace``d — a crash at any point leaves
        either the full old journal or the full new one.  Snapshot
        records are stamped with the CURRENT epoch and with sequence
        numbers ABOVE the old watermark, so seq stays monotonic and
        every outstanding sync ticket ends up covered.  Returns the
        number of snapshot records written."""
        recs = list(records)
        with self._sync_mutex:
            with self._lock:
                if self._fh is None:
                    raise JournalError(
                        f"journal {self.path} not open (call open() first)")
                if self._authority is not None:
                    current = self._authority()
                    if current > self.epoch:
                        self.stale_epoch_refusals += 1
                        log.error(
                            "journal %s: COMPACT REFUSED — fencing epoch "
                            "%d superseded by %d", self.path, self.epoch,
                            current)
                        raise StaleEpochError(
                            f"compact refused: epoch {self.epoch} "
                            f"superseded by {current}")
                seq = self._seq
                tmp = self.path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    for op, gang, data in recs:
                        if op not in JOURNAL_OPS:
                            raise JournalError(
                                f"unknown journal op {op!r} in compaction "
                                f"snapshot")
                        seq += 1
                        rec: Dict[str, Any] = {
                            "v": JOURNAL_VERSION,
                            "seq": seq,
                            "epoch": self.epoch,
                            "t": time.time(),
                            "op": op,
                            "gang": gang,
                            "data": data,
                        }
                        rec["sha"] = _sha(rec)
                        f.write(json.dumps(rec, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                old = self._fh
                self._fh = open(self.path, "a", encoding="utf-8")
                try:
                    old.close()
                except OSError:
                    pass
                self._seq = seq
                self.compactions_total += 1
                self.fsyncs_total += 1
            with self._sync_cond:
                # the snapshot (which subsumes every earlier record) is
                # durable: cover all outstanding tickets
                if seq > self._durable_seq:
                    self._durable_seq = seq
                self._sync_cond.notify_all()
        log.info("journal %s: compacted to %d snapshot records (seq %d)",
                 self.path, len(recs), seq)
        return len(recs)

    # -- bookkeeping -------------------------------------------------------

    def note_replay(self, records: int, conflicts: int) -> None:
        """Recorded by the admitter after restore_from_journal."""
        with self._lock:
            self.replay_records = records
            self.replay_conflicts = conflicts

    def snapshot(self) -> Dict[str, int]:
        """Metrics snapshot (kubedl_journal_* family)."""
        with self._lock:
            return {
                "appends_total": self.appends_total,
                "fsyncs_total": self.fsyncs_total,
                "compactions_total": self.compactions_total,
                "replay_records_total": self.replay_records,
                "replay_conflicts_total": self.replay_conflicts,
                "stale_epoch_refusals_total": self.stale_epoch_refusals,
                "epoch": self.epoch,
                "seq": self._seq,
            }

    def close(self) -> None:
        with self._sync_mutex:
            with self._lock:
                seq = self._seq
                if self._fh is not None:
                    try:
                        self._fh.flush()
                        os.fsync(self._fh.fileno())
                        self.fsyncs_total += 1
                    except (OSError, ValueError):
                        pass
                    try:
                        self._fh.close()
                    finally:
                        self._fh = None
            with self._sync_cond:
                if seq > self._durable_seq:
                    self._durable_seq = seq
                self._sync_cond.notify_all()
