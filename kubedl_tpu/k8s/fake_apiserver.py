"""Embedded fake kube-apiserver — hermetic wire-protocol test harness.

The reference tests against fake clients only (SURVEY.md §4: "no envtest
binaries"); this goes one step further and serves the actual HTTP wire
protocol so KubeClient/KubeObjectStore are exercised end-to-end: JSON
CRUD with resourceVersion optimistic concurrency (409 Conflict), 404/409
errors, labelSelector lists, chunked watch streams, and the /apis
discovery endpoints the workload gate's `auto` mode probes
(ref pkg/util/workloadgate/workload_gate.go:26-107).

State is raw JSON dicts — the server never imports the typed API, so a
client bug can't be masked by sharing dataclasses with the store under
test.
"""
from __future__ import annotations

import copy
import json
import re
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

# /api/v1/namespaces/{ns}/{plural}[/{name}[/status]]
_CORE_RE = re.compile(
    r"^/api/v1/namespaces/([^/]+)/([^/]+)(?:/([^/]+)(?:/(status))?)?$"
)
# /apis/{group}/{version}/namespaces/{ns}/{plural}[/{name}[/status]]
_GROUP_RE = re.compile(
    r"^/apis/([^/]+)/([^/]+)/namespaces/([^/]+)/([^/]+)(?:/([^/]+)(?:/(status))?)?$"
)
# cluster-scoped core resources, e.g. /api/v1/nodes[/{name}[/status]]
_CLUSTER_RE = re.compile(r"^/api/v1/([^/]+)(?:/([^/]+)(?:/(status))?)?$")
_DISCOVERY_RE = re.compile(r"^/apis/([^/]+)/([^/]+)$")

# namespace key used for cluster-scoped objects in the state buckets
CLUSTER_NS = ""

# apiserver-owned finalizer installed by propagationPolicy=Foreground.
# Deliberately a literal, NOT an import of api.meta.FOREGROUND_FINALIZER:
# this server never imports the typed API (see module docstring), so a
# typo in either copy shows up as a cross-backend fidelity test failure
# (tests/test_cascade_gc.py) instead of being masked by sharing.
_FOREGROUND = "foregroundDeletion"


def _now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _finalizers(obj: Dict) -> List[str]:
    return obj.get("metadata", {}).get("finalizers") or []


def _remove_obj(st: "_State", gv: str, plural: str, key, obj: Dict) -> None:
    """Physically remove (caller holds the lock): emit DELETED, drop the
    uid, wake the sweeper if anything owned it."""
    if st.objects.get((gv, plural), {}).get(key) is not obj:
        return  # re-created meanwhile
    st.objects[(gv, plural)].pop(key)
    meta = obj.setdefault("metadata", {})
    # deletes bump rv like a real apiserver — also what keeps every
    # event-log seq unique so watch replay-from-rv can't skip one
    meta["resourceVersion"] = st.next_rv()
    meta.setdefault("deletionTimestamp", _now_rfc3339())
    st.uids.discard(meta.get("uid"))
    st.track_refs(obj, -1)
    # owners wake the sweeper to reap dependents; owned leaves wake it in
    # case their owner is foreground-waiting on them
    if meta.get("uid") in st.ref_uids or meta.get("ownerReferences"):
        st.gc_wake.set()
    st.emit("DELETED", gv, plural, obj)


def _mark_deleting(st: "_State", gv: str, plural: str, obj: Dict) -> None:
    """Finalizer-blocked delete: the object stays, deletionTimestamp set,
    until the last finalizer is stripped by a PUT."""
    meta = obj.setdefault("metadata", {})
    if not meta.get("deletionTimestamp"):
        meta["deletionTimestamp"] = _now_rfc3339()
        meta["resourceVersion"] = st.next_rv()
        st.emit("MODIFIED", gv, plural, obj)
    st.gc_wake.set()


def _orphan_dependents(st: "_State", uid: str) -> None:
    """propagationPolicy=Orphan: strip the deleted owner's refs from all
    dependents so the GC never collects them."""
    for (gv2, plural2), bucket2 in st.objects.items():
        for dep in list(bucket2.values()):
            refs = dep.get("metadata", {}).get("ownerReferences") or []
            keep = [r for r in refs if r.get("uid") != uid]
            if len(keep) == len(refs):
                continue
            st.track_refs(dep, -1)
            dep["metadata"]["ownerReferences"] = keep
            st.track_refs(dep, +1)
            dep["metadata"]["resourceVersion"] = st.next_rv()
            st.emit("MODIFIED", gv2, plural2, dep)
            kept = [r for r in keep if isinstance(r, dict) and r.get("uid")]
            if kept and all(r["uid"] not in st.uids for r in kept):
                # surviving refs all point at dead owners — the strip
                # just created an orphan the sweeper must collect
                st.gc_wake.set()


class _State:
    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.rv = 0
        # (gv, plural) -> {(ns, name): object dict}
        self.objects: Dict[Tuple[str, str], Dict[Tuple[str, str], Dict]] = {}
        # registered resources: (gv, plural) -> kind
        self.resources: Dict[Tuple[str, str], str] = {}
        # (gv, plural) -> openAPIV3 structural schema; writes are PRUNED
        # against it like a real apiserver (unknown spec fields dropped
        # unless x-kubernetes-preserve-unknown-fields)
        self.schemas: Dict[Tuple[str, str], Dict] = {}
        # resources serving a /status subresource: main-path writes have
        # their status silently dropped, like a real apiserver with
        # `subresources: status: {}` in the CRD
        self.status_subresources: set = set()
        # cluster-scoped resources (no namespace segment), e.g. ("v1","nodes")
        self.cluster_resources: set = set()
        self.watchers: List["_Watcher"] = []
        self.uid = 0
        # (method, path-sans-query, is_watch) per request — lets tests
        # assert the informer cache eliminated hot-path HTTP traffic
        self.requests: List[Tuple[str, str, bool]] = []
        # garbage collection: set on owner deletion (and on writes that
        # leave an object pointing at a missing owner) to wake the GC
        # sweeper — the real apiserver's counterpart is the
        # kube-controller-manager GC that cascade-deletes dependents via
        # ownerReferences. uids/ref_uids are maintained incrementally so
        # the orphan checks on the request path are O(refs), not a
        # full-store scan under the global lock.
        self.gc_wake = threading.Event()
        self.uids: set = set()
        self.ref_uids: Dict[str, int] = {}
        # bounded event history so a watch from resourceVersion=N can
        # replay the events AFTER N with their TRUE types — without it a
        # modify landing between a client's list and its watch subscribe
        # replays as a duplicate ADDED (current-state synthesis), which
        # real apiservers never do
        self.event_log: "deque" = deque(maxlen=1024)

    @staticmethod
    def refs_of(obj: Dict) -> List[Dict]:
        return [
            r for r in obj.get("metadata", {}).get("ownerReferences") or []
            if isinstance(r, dict) and r.get("uid")
        ]

    def track_refs(self, obj: Dict, sign: int) -> None:
        """Caller holds the lock; sign is +1 (refs appear) or -1 (vanish)."""
        for r in self.refs_of(obj):
            n = self.ref_uids.get(r["uid"], 0) + sign
            if n > 0:
                self.ref_uids[r["uid"]] = n
            else:
                self.ref_uids.pop(r["uid"], None)

    def next_rv(self) -> str:
        self.rv += 1
        return str(self.rv)

    def emit(self, etype: str, gv: str, plural: str, obj: Dict) -> None:
        # deep copy: several paths mutate the stored dict in place, and a
        # replayed event must show the object as it was at emit time
        self.event_log.append({
            "seq": self.rv, "type": etype, "gv": gv, "plural": plural,
            "object": copy.deepcopy(obj)})
        for w in list(self.watchers):
            w.offer(etype, gv, plural, obj)


class _Watcher:
    def __init__(self, gv: str, plural: str, namespace: str) -> None:
        self.gv = gv
        self.plural = plural
        self.namespace = namespace
        self.events: "list" = []
        self.cond = threading.Condition()
        self.closed = False

    def offer(self, etype: str, gv: str, plural: str, obj: Dict) -> None:
        if (gv, plural) != (self.gv, self.plural):
            return
        if obj.get("metadata", {}).get("namespace") != self.namespace:
            return
        with self.cond:
            self.events.append({"type": etype, "object": obj})
            self.cond.notify_all()

    def take(self, timeout: float) -> List[Dict]:
        with self.cond:
            if not self.events:
                self.cond.wait(timeout)
            out, self.events = self.events, []
            return out


def _match_selector(labels: Dict[str, str], selector: str) -> bool:
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        k, _, v = clause.partition("=")
        if labels.get(k) != v:
            return False
    return True


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "FakeKubeApiserver/1.0"

    # quiet the default per-request stderr logging
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    @property
    def state(self) -> _State:
        return self.server.state  # type: ignore[attr-defined]

    def _send_json(self, status: int, body: Dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _error(self, status: int, message: str, reason: str) -> None:
        self._send_json(status, {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "message": message, "reason": reason, "code": status,
        })

    def _record(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        is_watch = "watch=true" in (parsed.query or "")
        st = self.state
        with st.lock:
            st.requests.append((method, parsed.path, is_watch))

    def _auth_ok(self) -> bool:
        token = self.server.token  # type: ignore[attr-defined]
        if not token:
            return True
        if self.headers.get("Authorization") == f"Bearer {token}":
            return True
        self._error(401, "Unauthorized", "Unauthorized")
        return False

    def _route(self) -> Optional[Tuple[str, str, str, Optional[str], Optional[str]]]:
        """-> (gv, plural, namespace, name, subresource) or None."""
        path = urllib.parse.urlparse(self.path).path
        m = _CORE_RE.match(path)
        if m:
            ns, plural, name, sub = m.groups()
            return "v1", plural, ns, name, sub
        m = _GROUP_RE.match(path)
        if m:
            group, version, ns, plural, name, sub = m.groups()
            return f"{group}/{version}", plural, ns, name, sub
        m = _CLUSTER_RE.match(path)
        if m:
            plural, name, sub = m.groups()
            if ("v1", plural) in self.state.cluster_resources:
                return "v1", plural, CLUSTER_NS, name, sub
        return None

    def _params(self) -> Dict[str, str]:
        qs = urllib.parse.urlparse(self.path).query
        return {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()}

    # -- discovery --------------------------------------------------------

    def _discovery(self, path: str) -> bool:
        st = self.state
        if path == "/api/v1":
            gv = "v1"
        else:
            m = _DISCOVERY_RE.match(path)
            if m:
                gv = f"{m.group(1)}/{m.group(2)}"
            elif path == "/apis":
                with st.lock:
                    groups = sorted({gv.split("/")[0] for gv, _ in st.resources if "/" in gv})
                self._send_json(200, {
                    "kind": "APIGroupList",
                    "groups": [{"name": g, "versions": []} for g in groups],
                })
                return True
            else:
                return False
        with st.lock:
            resources = [
                {"name": plural, "kind": kind, "namespaced": True}
                for (g, plural), kind in sorted(st.resources.items())
                if g == gv
            ]
        self._send_json(200, {
            "kind": "APIResourceList", "groupVersion": gv, "resources": resources,
        })
        return True

    # -- verbs ------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        self._record("GET")
        if not self._auth_ok():
            return
        path = urllib.parse.urlparse(self.path).path
        if self._discovery(path):
            return
        route = self._route()
        if route is None:
            return self._error(404, f"unknown path {path}", "NotFound")
        gv, plural, ns, name, sub = route
        st = self.state
        if (gv, plural) not in st.resources:
            return self._error(404, f"resource {gv}/{plural} not registered", "NotFound")
        if sub and (gv, plural) not in st.status_subresources:
            return self._error(404, f"{plural} has no status subresource", "NotFound")
        if name:
            with st.lock:
                obj = st.objects.get((gv, plural), {}).get((ns, name))
            if obj is None:
                return self._error(404, f"{plural} {ns}/{name} not found", "NotFound")
            # GET of /status returns the whole object, like the real thing
            return self._send_json(200, obj)
        params = self._params()
        if params.get("watch") == "true":
            return self._watch(gv, plural, ns, params)
        selector = params.get("labelSelector", "")
        with st.lock:
            items = [
                o for (ons, _), o in sorted(st.objects.get((gv, plural), {}).items())
                if ons == ns
                and _match_selector(o.get("metadata", {}).get("labels") or {}, selector)
            ]
            rv = str(st.rv)
        self._send_json(200, {
            "kind": "List", "apiVersion": gv,
            "metadata": {"resourceVersion": rv}, "items": items,
        })

    def _watch(self, gv: str, plural: str, ns: str, params: Dict[str, str]) -> None:
        st = self.state
        w = _Watcher(gv, plural, ns)
        since = int(params.get("resourceVersion", "0") or "0")
        with st.lock:
            log = list(st.event_log)
            # gapless iff no event after `since` has aged out of the log
            gapless = (since >= log[0]["seq"] - 1) if log else (st.rv <= since)
            if gapless:
                # replay the actual events after `since`, true types kept
                backlog = [
                    {"type": e["type"], "object": e["object"]}
                    for e in log
                    if e["seq"] > since
                    and (e["gv"], e["plural"]) == (gv, plural)
                    and e["object"].get("metadata", {}).get("namespace") == ns
                ]
            else:
                # history window lost (real apiserver would 410; clients
                # here already relist on gaps): synthesize current state
                backlog = [
                    {"type": "ADDED", "object": o}
                    for (ons, _), o in sorted(
                        st.objects.get((gv, plural), {}).items())
                    if ons == ns
                    and int(o.get("metadata", {}).get(
                        "resourceVersion", "0")) > since
                ]
            st.watchers.append(w)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        try:
            for ev in backlog:
                send_chunk(json.dumps(ev).encode() + b"\n")
            while not w.closed:
                for ev in w.take(timeout=0.5):
                    send_chunk(json.dumps(ev).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            with st.lock:
                if w in st.watchers:
                    st.watchers.remove(w)
            self.close_connection = True

    def _read_body(self) -> Optional[Dict]:
        length = int(self.headers.get("Content-Length", "0") or "0")
        if not length:
            return None
        return json.loads(self.rfile.read(length))

    def do_POST(self) -> None:  # noqa: N802
        self._record("POST")
        if not self._auth_ok():
            return
        route = self._route()
        if route is None:
            return self._error(404, "unknown path", "NotFound")
        gv, plural, ns, _, sub = route
        st = self.state
        if sub:
            return self._error(405, "create not allowed on subresource", "MethodNotAllowed")
        if (gv, plural) not in st.resources:
            return self._error(404, f"resource {gv}/{plural} not registered", "NotFound")
        obj = self._read_body() or {}
        # status is reset on create for subresource-enabled kinds — the
        # apiserver owns the main path, status owners write /status later
        if (gv, plural) in st.status_subresources:
            obj.pop("status", None)
        schema = st.schemas.get((gv, plural))
        if schema is not None:
            from kubedl_tpu.utils.schema import prune

            prune(obj, schema)
        meta = obj.setdefault("metadata", {})
        meta["namespace"] = ns
        name = meta.get("name", "")
        if not name:
            return self._error(422, "metadata.name required", "Invalid")
        with st.lock:
            bucket = st.objects.setdefault((gv, plural), {})
            if (ns, name) in bucket:
                return self._error(
                    409, f'{plural} "{name}" already exists', "AlreadyExists"
                )
            st.uid += 1
            meta.setdefault("uid", f"fake-uid-{st.uid}")
            meta.setdefault("creationTimestamp", time.time())
            meta["generation"] = 1
            meta["resourceVersion"] = st.next_rv()
            bucket[(ns, name)] = obj
            st.uids.add(meta["uid"])
            st.track_refs(obj, +1)
            st.emit("ADDED", gv, plural, obj)
            refs = st.refs_of(obj)
            if refs and all(r["uid"] not in st.uids for r in refs):
                # born orphaned (owner deleted between the client's read
                # and this create) — GC must collect it
                st.gc_wake.set()
        self._send_json(201, obj)

    def do_PUT(self) -> None:  # noqa: N802
        self._record("PUT")
        if not self._auth_ok():
            return
        route = self._route()
        if route is None or route[3] is None:
            return self._error(404, "unknown path", "NotFound")
        gv, plural, ns, name, sub = route
        st = self.state
        has_status = (gv, plural) in st.status_subresources
        if sub and not has_status:
            return self._error(404, f"{plural} has no status subresource", "NotFound")
        obj = self._read_body() or {}
        if not sub:
            schema = st.schemas.get((gv, plural))
            if schema is not None:
                from kubedl_tpu.utils.schema import prune

                prune(obj, schema)
        meta = obj.setdefault("metadata", {})
        meta["namespace"] = ns
        meta["name"] = name
        with st.lock:
            bucket = st.objects.setdefault((gv, plural), {})
            cur = bucket.get((ns, name))
            if cur is None:
                return self._error(404, f"{plural} {ns}/{name} not found", "NotFound")
            cur_rv = cur.get("metadata", {}).get("resourceVersion")
            if str(meta.get("resourceVersion", "")) != str(cur_rv):
                return self._error(
                    409,
                    f"Operation cannot be fulfilled on {plural} {name!r}: "
                    f"the object has been modified",
                    "Conflict",
                )
            if sub:
                # /status PUT: only the status (and nothing else) changes
                # — and metadata.generation never moves for status writes
                new = json.loads(json.dumps(cur))
                if "status" in obj:
                    new["status"] = obj["status"]
                else:
                    new.pop("status", None)
                obj = new
            else:
                meta["uid"] = cur["metadata"].get("uid")
                meta["creationTimestamp"] = cur["metadata"].get("creationTimestamp")
                # deletionTimestamp is apiserver-owned; once deleting, no
                # NEW finalizers may be added (kube ValidateObjectMetaUpdate)
                if cur["metadata"].get("deletionTimestamp"):
                    meta["deletionTimestamp"] = cur["metadata"]["deletionTimestamp"]
                    added = set(_finalizers(obj)) - set(_finalizers(cur))
                    if added:
                        return self._error(
                            403,
                            "no new finalizers can be added if the object "
                            f"is being deleted (tried {sorted(added)})",
                            "Forbidden",
                        )
                else:
                    meta.pop("deletionTimestamp", None)
                if has_status:
                    # main-path PUT: incoming status is SILENTLY dropped —
                    # the exact real-apiserver behavior that makes missing
                    # update_status() calls a production bug
                    if "status" in cur:
                        obj["status"] = cur["status"]
                    else:
                        obj.pop("status", None)
                # metadata.generation increments iff the DESIRED state
                # (anything outside metadata/status) changed — label or
                # annotation churn must not look like a new spec
                old_gen = int(cur["metadata"].get("generation", 1) or 1)
                desired = {k: v for k, v in obj.items()
                           if k not in ("metadata", "status")}
                cur_desired = {k: v for k, v in cur.items()
                               if k not in ("metadata", "status")}
                meta["generation"] = (
                    old_gen + 1 if desired != cur_desired else old_gen)
            obj["metadata"]["resourceVersion"] = st.next_rv()
            st.track_refs(cur, -1)  # ownerRefs may change (orphan release)
            st.track_refs(obj, +1)
            bucket[(ns, name)] = obj
            st.emit("MODIFIED", gv, plural, obj)
            refs = st.refs_of(obj)
            if refs and all(r["uid"] not in st.uids for r in refs):
                # adopted onto an already-dead owner — GC must collect
                st.gc_wake.set()
            if obj["metadata"].get("deletionTimestamp") and not _finalizers(obj):
                # last finalizer stripped — the pending delete completes
                _remove_obj(st, gv, plural, (ns, name), obj)
        self._send_json(200, obj)

    def do_DELETE(self) -> None:  # noqa: N802
        self._record("DELETE")
        if not self._auth_ok():
            return
        route = self._route()
        if route is None or route[3] is None:
            return self._error(404, "unknown path", "NotFound")
        gv, plural, ns, name, sub = route
        if sub:
            return self._error(405, "delete not allowed on subresource", "MethodNotAllowed")
        propagation = self._params().get("propagationPolicy", "Background")
        if propagation not in ("Background", "Foreground", "Orphan"):
            return self._error(
                400, f"unknown propagationPolicy {propagation!r}", "BadRequest")
        st = self.state
        with st.lock:
            bucket = st.objects.get((gv, plural), {})
            obj = bucket.get((ns, name))
            if obj is None:
                return self._error(404, f"{plural} {ns}/{name} not found", "NotFound")
            meta = obj.setdefault("metadata", {})
            uid = meta.get("uid")
            if propagation == "Orphan":
                _orphan_dependents(st, uid)
            elif propagation == "Foreground":
                if _FOREGROUND not in _finalizers(obj):
                    meta["finalizers"] = _finalizers(obj) + [_FOREGROUND]
            if _finalizers(obj):
                # finalizer-blocked: only mark; removal happens when the
                # last finalizer is stripped (or the foreground GC is done)
                _mark_deleting(st, gv, plural, obj)
            else:
                _remove_obj(st, gv, plural, (ns, name), obj)
        self._send_json(200, obj)


class FakeApiServer:
    """`with FakeApiServer() as srv: KubeClient(srv.url)` — that's the API."""

    def __init__(self, token: Optional[str] = None) -> None:
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self._httpd.state = _State()  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self.register_resource("v1", "pods", "Pod", status_subresource=True)
        self.register_resource("v1", "services", "Service")
        self.register_resource("v1", "events", "Event")
        self.register_resource("coordination.k8s.io/v1", "leases", "Lease")
        self.register_resource("v1", "nodes", "Node", namespaced=False)
        self.register_resource(
            "scheduling.kubedl-tpu.io/v1alpha1", "podgroups", "PodGroup",
            status_subresource=True,
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def register_resource(
        self,
        gv: str,
        plural: str,
        kind: str,
        status_subresource: bool = False,
        namespaced: bool = True,
        schema: Optional[Dict] = None,
    ) -> None:
        state: _State = self._httpd.state  # type: ignore[attr-defined]
        with state.lock:
            state.resources[(gv, plural)] = kind
            if status_subresource:
                state.status_subresources.add((gv, plural))
            if not namespaced:
                state.cluster_resources.add((gv, plural))
            if schema is not None:
                state.schemas[(gv, plural)] = schema

    def register_workload_crds(self) -> None:
        from kubedl_tpu.k8s.resources import register_workload_kinds, registered_kinds
        from kubedl_tpu.utils.schema import schema_for_job

        register_workload_kinds()
        for kind, info in registered_kinds().items():
            # CRDs (non-core groups) get the structural schema generated
            # from their typed API class, so writes are pruned like on a
            # real cluster. Core v1 kinds (Pod/Service/Event) stay
            # unpruned: our typed classes model a SUBSET of core v1, and
            # a real apiserver admits the full surface there.
            is_crd = "/" in info.api_version
            self.register_resource(
                info.api_version, info.plural, kind,
                status_subresource=info.status_subresource,
                schema=schema_for_job(info.cls)
                if (is_crd and info.cls) else None,
            )

    def start(self) -> "FakeApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-apiserver", daemon=True
        )
        self._thread.start()
        self._gc_stop = threading.Event()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, name="fake-apiserver-gc", daemon=True
        )
        self._gc_thread.start()
        return self

    def stop(self) -> None:
        if getattr(self, "_gc_stop", None) is not None:
            self._gc_stop.set()
            self._httpd.state.gc_wake.set()  # type: ignore[attr-defined]
            self._gc_thread.join(timeout=2.0)
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- garbage collection ------------------------------------------------
    # The real cluster's kube-controller-manager GC cascade-deletes
    # dependents whose ownerReferences all point at deleted uids (the
    # contract the reference relies on: job_controller.go:114-126 sets
    # Controller+BlockOwnerDeletion refs and lets Kubernetes reap pods).
    # Without this the harness certifies away every cascade-dependent
    # behavior (VERDICT r3 missing #1).

    def _gc_loop(self) -> None:
        st: _State = self._httpd.state  # type: ignore[attr-defined]
        while not self._gc_stop.is_set():
            st.gc_wake.wait()
            st.gc_wake.clear()
            if self._gc_stop.is_set():
                return
            try:
                self._gc_sweep(st)
            except Exception:  # noqa: BLE001 — one malformed object must
                pass  # not permanently kill cascade deletion

    @staticmethod
    def _gc_sweep(st: _State) -> None:
        while True:
            acted = False
            with st.lock:
                # 1) orphans: every ownerRef uid is gone
                for (gv, plural), bucket in list(st.objects.items()):
                    for key, obj in list(bucket.items()):
                        refs = st.refs_of(obj)
                        if not refs or any(r["uid"] in st.uids for r in refs):
                            continue
                        if _finalizers(obj):
                            if not obj.get("metadata", {}).get("deletionTimestamp"):
                                _mark_deleting(st, gv, plural, obj)
                                acted = True
                        else:
                            _remove_obj(st, gv, plural, key, obj)
                            acted = True
                # 2) foreground-deleting owners: reap dependents, then
                # strip the foregroundDeletion finalizer once no
                # blockOwnerDeletion dependent remains
                owners = [
                    (gv, plural, key, obj)
                    for (gv, plural), bucket in st.objects.items()
                    for key, obj in list(bucket.items())
                    if obj.get("metadata", {}).get("deletionTimestamp")
                    and _FOREGROUND in _finalizers(obj)
                ]
                for gv, plural, key, owner in owners:
                    uid = owner["metadata"].get("uid")
                    blocked = False
                    for (gv2, plural2), bucket2 in list(st.objects.items()):
                        for key2, dep in list(bucket2.items()):
                            refs = [r for r in st.refs_of(dep) if r["uid"] == uid]
                            if not refs:
                                continue
                            # a dependent with ANOTHER live owner is not
                            # deleted by this owner's foreground pass
                            # (and does not block it)
                            if any(r["uid"] != uid and r["uid"] in st.uids
                                   for r in st.refs_of(dep)):
                                continue
                            if _finalizers(dep):
                                if not dep.get("metadata", {}).get("deletionTimestamp"):
                                    _mark_deleting(st, gv2, plural2, dep)
                                    acted = True
                                if any(r.get("blockOwnerDeletion") for r in refs):
                                    blocked = True
                            else:
                                _remove_obj(st, gv2, plural2, key2, dep)
                                acted = True
                    if not blocked:
                        meta = owner["metadata"]
                        meta["finalizers"] = [
                            f for f in _finalizers(owner) if f != _FOREGROUND]
                        if meta["finalizers"]:
                            meta["resourceVersion"] = st.next_rv()
                            st.emit("MODIFIED", gv, plural, owner)
                        else:
                            _remove_obj(st, gv, plural, key, owner)
                        acted = True
            if not acted:
                return

    def __enter__(self) -> "FakeApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
