"""KubeObjectStore — the core ObjectStore surface over a kube-apiserver.

The reconcile engine (controllers/engine.py) and manager (core/manager.py)
run unmodified over either store: create/get/update/delete/list raise the
same NotFound/AlreadyExists/Conflict, and watch() yields the same
WatchEvent stream (initial list replayed as ADDED, informer-style, then
live events with reconnect-on-drop). Objects cross the boundary as the
same typed dataclasses; serde translates to/from the k8s JSON wire, with
resourceVersion mapped str<->int at this edge.

Ref: this replaces what controller-runtime's client+informer cache do for
the reference (L0, SURVEY.md §1).
"""
from __future__ import annotations

import copy
import logging
import queue
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from kubedl_tpu.core.store import (
    ADDED,
    DELETED,
    AlreadyExists,
    Conflict,
    NotFound,
    StoreError,
    WatchEvent,
)
from kubedl_tpu.k8s.client import KubeApiError, KubeClient
from kubedl_tpu.k8s.resources import register_workload_kinds, resource_for
from kubedl_tpu.utils.serde import from_dict, to_dict

log = logging.getLogger("kubedl_tpu.k8s.store")


# -- k8s wire translation ---------------------------------------------------
# Internal API types diverge from the k8s wire in three places: env is a
# plain dict (k8s: list of {name, value}), resource quantities are floats
# (k8s: strings like "500m"/"1Gi"), and resourceVersion is an int (k8s:
# string). Translate at this edge so a REAL apiserver accepts our pods.

from kubedl_tpu.utils.serde import parse_quantity as _quantity_to_float


def _float_to_quantity(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    milli = v * 1000
    if milli.is_integer():
        return f"{int(milli)}m"
    return str(v)


def _pod_spec_to_wire(spec: Dict) -> None:
    for key in ("containers", "initContainers"):
        for c in spec.get(key) or []:
            env = c.get("env")
            if isinstance(env, dict):
                # envRaw entries (valueFrom etc., preserved by decode) go
                # first; plain vars follow in INSERTION order — kubelet
                # expands $(VAR) only from earlier entries, so sorting
                # would break dependent env vars.
                raw = c.pop("envRaw", None) or []
                raw_names = {e.get("name") for e in raw}
                c["env"] = list(raw) + [
                    {"name": k, "value": str(v)}
                    for k, v in env.items() if k not in raw_names
                ]
            res = c.get("resources")
            if isinstance(res, dict):
                for rk in ("requests", "limits"):
                    if isinstance(res.get(rk), dict):
                        res[rk] = {k: _float_to_quantity(v) for k, v in res[rk].items()}


def _pod_spec_from_wire(spec: Dict) -> None:
    for key in ("containers", "initContainers"):
        for c in spec.get(key) or []:
            env = c.get("env")
            if isinstance(env, list):
                # split: plain name/value pairs -> the internal dict;
                # valueFrom-style entries -> envRaw so an update round-trip
                # can't strip a secretKeyRef into an empty string
                plain, raw = {}, []
                for e in env:
                    if "name" not in e:
                        continue
                    if set(e) <= {"name", "value"}:
                        plain[e["name"]] = e.get("value", "")
                    else:
                        raw.append(e)
                c["env"] = plain
                if raw:
                    c["envRaw"] = raw
            res = c.get("resources")
            if isinstance(res, dict):
                for rk in ("requests", "limits"):
                    if isinstance(res.get(rk), dict):
                        res[rk] = {
                            k: _quantity_to_float(v) for k, v in res[rk].items()
                        }


def _walk_pod_specs(body: Dict, kind: str, fn) -> None:
    if kind == "Pod":
        if isinstance(body.get("spec"), dict):
            fn(body["spec"])
        return
    # workload kinds: every replica template carries a pod spec
    spec = body.get("spec")
    if not isinstance(spec, dict):
        return
    for k, v in spec.items():
        if k.endswith("ReplicaSpecs") or k == "replicaSpecs":
            for rspec in (v or {}).values():
                tmpl_spec = ((rspec or {}).get("template") or {}).get("spec")
                if isinstance(tmpl_spec, dict):
                    fn(tmpl_spec)


def _encode(obj) -> Dict:
    info = resource_for(obj.kind)
    body = to_dict(obj)
    body["apiVersion"] = info.api_version
    body["kind"] = obj.kind
    meta = body.setdefault("metadata", {})
    rv = meta.pop("resourceVersion", None)
    if rv:
        meta["resourceVersion"] = str(rv)
    _walk_pod_specs(body, obj.kind, _pod_spec_to_wire)
    return body


def _decode(kind: str, body: Dict):
    info = resource_for(kind)
    body = dict(body)
    meta = dict(body.get("metadata") or {})
    rv = meta.get("resourceVersion")
    if rv is not None:
        meta["resourceVersion"] = int(rv)
    body["metadata"] = meta
    _walk_pod_specs(body, kind, _pod_spec_from_wire)
    if info.cls is None:
        return body
    obj = from_dict(info.cls, body)
    obj.kind = kind
    return obj


def _selector_param(label_selector: Optional[Dict[str, str]]) -> Dict[str, str]:
    if not label_selector:
        return {}
    return {"labelSelector": ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))}


class _InformerCache:
    """Watch-synced read cache — the informer half of controller-runtime.

    Fed by the KubeWatch pump that owns each kind (cache applied BEFORE the
    event is delivered, so a reconcile triggered by an event always sees a
    cache at least as new as the event). `get`/`list` serve from here once
    a kind is synced, making the reconcile hot path HTTP-free — the
    reference reads from the informer cache the same way (SURVEY §3.2,
    ref pkg/job_controller/job.go:106-116)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._synced: Dict[str, bool] = {}
        # kind -> (ns, name) -> decoded object
        self._objects: Dict[str, Dict[tuple, Any]] = {}

    _NOT_SYNCED = object()  # sentinel: caller must fall back to HTTP

    def synced(self, kind: str) -> bool:
        with self._lock:
            return self._synced.get(kind, False)

    def begin_sync(self, kind: str) -> None:
        with self._lock:
            self._synced[kind] = False
            self._objects[kind] = {}

    def mark_synced(self, kind: str) -> None:
        with self._lock:
            self._synced[kind] = True

    def apply(self, etype: str, kind: str, obj) -> None:
        key = (obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            bucket = self._objects.setdefault(kind, {})
            if etype == DELETED:
                bucket.pop(key, None)
                return
            cur = bucket.get(key)
            # guard against replay of an older snapshot overwriting a
            # newer event (two pumps or a relist race)
            if cur is not None and cur.metadata.resource_version > obj.metadata.resource_version:
                return
            bucket[key] = obj

    def get(self, kind: str, namespace: str, name: str):
        """-> object copy, None (synced and absent), or _NOT_SYNCED.
        The synced check and the read share one lock acquisition, so a
        concurrent relist (begin_sync clears the bucket) can never serve
        an empty bucket as truth."""
        with self._lock:
            if not self._synced.get(kind, False):
                return self._NOT_SYNCED
            obj = self._objects.get(kind, {}).get((namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def list(self, kind: str, namespace: str, label_selector):
        """-> sorted list of copies, or _NOT_SYNCED (same atomicity note)."""
        with self._lock:
            if not self._synced.get(kind, False):
                return self._NOT_SYNCED
            items = [
                copy.deepcopy(o)
                for (ns, _), o in self._objects.get(kind, {}).items()
                if ns == namespace
                and all(
                    o.metadata.labels.get(k) == v
                    for k, v in (label_selector or {}).items()
                )
            ]
        items.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return items


class KubeObjectStore:
    def __init__(self, client: KubeClient, namespace: str = "default") -> None:
        register_workload_kinds()
        self.client = client
        self.default_namespace = namespace
        self._watchers: List["KubeWatch"] = []
        self.cache = _InformerCache()
        # kind -> the KubeWatch pump feeding the cache for that kind (one
        # informer per kind; extra watches don't double-feed)
        self._cache_feeders: Dict[str, "KubeWatch"] = {}
        self._feeder_lock = threading.Lock()

    # -- CRUD (same contract as core.store.ObjectStore) -------------------

    def create(self, obj):
        info = resource_for(obj.kind)
        try:
            body = self.client.request(
                "POST", info.path(obj.metadata.namespace), body=_encode(obj)
            )
        except KubeApiError as e:
            raise _map_error(e, obj.kind, self._key(obj)) from e
        return _decode(obj.kind, body)

    def get(self, kind: str, namespace: str, name: str):
        obj = self.cache.get(kind, namespace, name)
        if obj is _InformerCache._NOT_SYNCED:
            return self.get_fresh(kind, namespace, name)
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return obj

    def get_fresh(self, kind: str, namespace: str, name: str):
        """Uncached apiserver GET — for reads that must not be stale
        (adoption's deletion-timestamp recheck, status-write rv refresh;
        ref pkg/job_controller/util.go:33-49 uses the uncached reader)."""
        info = resource_for(kind)
        try:
            body = self.client.request("GET", info.path(namespace, name))
        except KubeApiError as e:
            raise _map_error(e, kind, f"{namespace}/{name}") from e
        return _decode(kind, body)

    def update(self, obj):
        info = resource_for(obj.kind)
        try:
            body = self.client.request(
                "PUT",
                info.path(obj.metadata.namespace, obj.metadata.name),
                body=_encode(obj),
            )
        except KubeApiError as e:
            raise _map_error(e, obj.kind, self._key(obj)) from e
        return _decode(obj.kind, body)

    def update_status(self, obj):
        """PUT to the `/status` subresource. Required for every kind whose
        CRD declares `subresources: status: {}` (all five workload CRDs +
        podgroups, config/crd/bases/) — a real apiserver silently drops
        status changes sent to the main resource path.
        Ref: controllers/tensorflow/job.go:95-104 r.Status().Update."""
        info = resource_for(obj.kind)
        if not info.status_subresource:
            return self.update(obj)
        try:
            body = self.client.request(
                "PUT",
                info.status_path(obj.metadata.namespace, obj.metadata.name),
                body=_encode(obj),
            )
        except KubeApiError as e:
            raise _map_error(e, obj.kind, self._key(obj)) from e
        return _decode(obj.kind, body)

    def delete(self, kind: str, namespace: str, name: str,
               propagation: str = "Background"):
        """DELETE with deletionPropagation ({Background,Foreground,Orphan})
        — wire twin of ObjectStore.delete(propagation=...)."""
        info = resource_for(kind)
        try:
            body = self.client.request(
                "DELETE", info.path(namespace, name),
                params={"propagationPolicy": propagation},
            )
        except KubeApiError as e:
            raise _map_error(e, kind, f"{namespace}/{name}") from e
        return _decode(kind, body) if body else None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        info = resource_for(kind)
        ns = namespace if namespace is not None else self.default_namespace
        cached = self.cache.list(kind, ns, label_selector)
        if cached is not _InformerCache._NOT_SYNCED:
            return cached
        try:
            body = self.client.request(
                "GET", info.path(ns), params=_selector_param(label_selector)
            )
        except KubeApiError as e:
            raise _map_error(e, kind, ns) from e
        items = []
        for item in body.get("items", []):
            items.append(_decode(kind, item))
        items.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return items

    # -- discovery (workload gate `auto`, ref workload_gate.go:26-107) ----

    def has_kind(self, kind: str) -> bool:
        """True iff the API server serves this kind's CRD.

        A 404 means "group/version not installed" -> False; any other
        error (apiserver blip, RBAC) raises, so a caller doing startup
        discovery fails loudly instead of silently disabling every
        workload (the operator pod then restarts and retries)."""
        info = resource_for(kind)
        try:
            body = self.client.request("GET", info.base_path())
        except KubeApiError as e:
            if e.status == 404:
                return False
            raise StoreError(f"discovery for {kind} failed: {e}") from e
        return any(r.get("kind") == kind for r in (body or {}).get("resources", []))

    # -- watch ------------------------------------------------------------

    def watch(
        self, kinds: Optional[List[str]] = None, cache_only: bool = False
    ) -> "KubeWatch":
        """cache_only=True feeds the informer cache without queueing
        events — for kinds nothing reconciles on (e.g. PodGroups, which
        the gang admitter reads per pass) where an undrained queue would
        grow unboundedly."""
        w = KubeWatch(self, kinds or [], cache_only=cache_only)
        self._watchers.append(w)
        w.start()
        return w

    def wait_for_cache_sync(self, kinds: List[str], timeout: float = 30.0) -> bool:
        """Block until the informer cache has replayed the initial list for
        every kind (controller-runtime's WaitForCacheSync). Returns False
        on timeout — callers keep running; reads just stay HTTP until the
        pumps catch up."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if all(self.cache.synced(k) for k in kinds):
                return True
            time.sleep(0.02)
        return all(self.cache.synced(k) for k in kinds)

    @staticmethod
    def _key(obj) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"


def _map_error(e: KubeApiError, kind: str, key: str) -> StoreError:
    if e.status == 404:
        return NotFound(f"{kind} {key} not found")
    if e.status == 409 and "already exists" in e.message.lower():
        return AlreadyExists(f"{kind} {key} already exists")
    if e.status == 409:
        return Conflict(f"{kind} {key}: {e.message}")
    return StoreError(f"{kind} {key}: {e}")


class KubeWatch:
    """One list+watch thread per kind, multiplexed into a single queue —
    the informer pattern. Reconnects with the last seen resourceVersion;
    relists on 410 Gone."""

    def __init__(
        self, store: KubeObjectStore, kinds: List[str], cache_only: bool = False
    ) -> None:
        self._store = store
        self._kinds = kinds
        self._cache_only = cache_only
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: list = []  # live watch connections, closed on stop()

    def start(self) -> None:
        for kind in self._kinds:
            t = threading.Thread(
                target=self._pump, args=(kind,), name=f"kubewatch-{kind}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _pump(self, kind: str) -> None:
        info = resource_for(kind)
        store = self._store
        ns = store.default_namespace
        # Claim the informer role for this kind: exactly one pump feeds
        # the read cache so two watches can't fight over relist resets.
        with store._feeder_lock:
            feeds_cache = store._cache_feeders.setdefault(kind, self) is self
        rv: Optional[str] = None
        try:
            while not self._stopped.is_set():
                try:
                    if rv is None:
                        if feeds_cache:
                            store.cache.begin_sync(kind)
                        body = store.client.request("GET", info.path(ns))
                        rv = str((body.get("metadata") or {}).get("resourceVersion", "0"))
                        for item in body.get("items", []):
                            self._offer(ADDED, kind, item, feeds_cache)
                        if feeds_cache:
                            store.cache.mark_synced(kind)
                    for etype, obj in store.client.watch(
                        info.path(ns), params={"resourceVersion": rv},
                        conn_holder=self._conns, abort=self._stopped.is_set,
                    ):
                        if self._stopped.is_set():
                            return
                        if etype == "ERROR":
                            rv = None  # 410 Gone mid-stream: relist
                            break
                        item_rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if item_rv is not None:
                            rv = str(item_rv)
                        self._offer(etype, kind, obj, feeds_cache)
                except KubeApiError as e:
                    if e.status == 410:
                        rv = None
                    self._stopped.wait(0.2)
                except Exception:  # noqa: BLE001 — transport blips: back off, retry
                    if not self._stopped.is_set():
                        self._stopped.wait(0.5)
        finally:
            if feeds_cache:
                with store._feeder_lock:
                    if store._cache_feeders.get(kind) is self:
                        del store._cache_feeders[kind]
                store.cache.begin_sync(kind)  # stale cache must not serve reads

    def _offer(self, etype: str, kind: str, body: Dict, feeds_cache: bool = False) -> None:
        try:
            obj = _decode(kind, body)
        except Exception:  # noqa: BLE001 — skip undecodable objects
            log.warning("undecodable %s watch event dropped", kind)
            return
        if feeds_cache:
            # cache BEFORE delivery: a reconcile woken by this event sees
            # a cache at least as fresh as the event itself
            self._store.cache.apply(etype, kind, obj)
        if not self._cache_only:
            self._q.put(WatchEvent(type=etype, kind=kind, obj=obj))

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self) -> None:
        self._stopped.set()
        # Unblock pumps parked in the chunked read so their feeder/cache
        # cleanup runs promptly. socket.shutdown (not conn.close) — close
        # would need the buffered reader's lock, which the blocked reader
        # thread holds, deadlocking the stopper.
        for conn in list(self._conns):
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        self._q.put(None)

    def join(self, timeout: float = 10.0) -> bool:
        """Block until every pump thread has exited (their `finally`
        blocks have run, so fed caches are already marked unsynced).
        Event-driven replacement for deadline-polling `cache.synced` in
        tests (the 90 s sleep-tuning VERDICT r3 weak #6 called out).
        Returns False if a pump is still alive after `timeout`."""
        deadline = time.monotonic() + timeout
        for t in self._threads:
            if t is threading.current_thread():
                continue
            t.join(max(deadline - time.monotonic(), 0.01))
        return not any(
            t.is_alive() for t in self._threads
            if t is not threading.current_thread()
        )
