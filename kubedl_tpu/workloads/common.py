"""Shared helpers for workload controllers.

The reference wires four different rendezvous schemes (TF_CONFIG JSON, torch
TCP-store env, Rabit tracker env, ZooKeeper namespaces). TPU-native jobs all
converge on ONE scheme — the JAX coordination service (SURVEY.md §2.4): the
reconciler injects coordinator address + process count + process id; XLA
collectives then ride ICI/DCN. `inject_coordinator_env` is that single
implementation; per-framework envs are kept for compatibility on top.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from kubedl_tpu.api.common import ReplicaSpec
from kubedl_tpu.controllers.utils import gen_general_name, get_total_replicas

# ref controllers/tensorflow/tensorflow.go:30-33
ENV_CUSTOM_CLUSTER_DOMAIN = "CUSTOM_CLUSTER_DOMAIN"

# Port of the JAX coordination service on worker 0 (the PJRT distributed
# runtime default).
COORDINATOR_PORT = 8471

# Port of the Megascale (multislice DCN) coordinator on slice-0 worker-0 —
# libtpu's default; injected as MEGASCALE_COORDINATOR_ADDRESS next to the
# coordination-service envs for numSlices > 1 jobs (workloads/jaxjob.py).
MEGASCALE_PORT = 8080

# Port each MPMD pipeline stage's transport plane listens on in kube
# mode (KUBEDL_TRANSPORT=socket): the neighbor addresses injected as
# KUBEDL_PP_PREV_ADDR/NEXT_ADDR point at the neighbor stage's worker-0
# service on this port, and the stage's own plane binds it via
# KUBEDL_TRANSPORT_BIND. The local executor's DirChannel lane doesn't
# dial it — see docs/transport.md and docs/pipeline.md "Transports".
PIPELINE_PORT = 8476

# Port each RL-fleet pod's transport plane listens on in kube mode
# (KUBEDL_TRANSPORT=socket): actors dial the learner's service on this
# port for trajectories, the learner dials each actor's for the weight
# broadcast (KUBEDL_RL_LEARNER_ADDR / KUBEDL_RL_ACTOR_ADDRS). The local
# executor's DirChannel lane rides KUBEDL_RL_QUEUE_DIR instead — see
# docs/rl.md "Transports".
RL_PORT = 8478

ENV_COORDINATOR_ADDRESS = "KUBEDL_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KUBEDL_NUM_PROCESSES"
ENV_PROCESS_ID = "KUBEDL_PROCESS_ID"


def service_dns(job, rt: str, index, namespace: Optional[str] = None) -> str:
    """Headless-service DNS name for one replica.

    Ref controllers/tensorflow/tensorflow.go:122-136: name-rtype-i.ns.svc
    plus CUSTOM_CLUSTER_DOMAIN when set.
    """
    host = gen_general_name(job.metadata.name, rt, index)
    svc = f"{host}.{namespace or job.metadata.namespace}.svc"
    domain = os.environ.get(ENV_CUSTOM_CLUSTER_DOMAIN, "")
    if domain:
        svc += f".{domain}"
    return svc


def get_port_from_specs(
    replica_specs: Dict[str, ReplicaSpec], rtype: str, container_name: str,
    port_name: str, default: int,
) -> int:
    """Named port of the default container for a replica type
    (ref pkg/job_controller/service.go:221-234)."""
    spec = replica_specs.get(rtype)
    if spec is None:
        return default
    for c in spec.template.spec.containers:
        if c.name == container_name:
            p = c.port_named(port_name)
            if p:
                return p
    return default


def add_env(pod_template, env: Dict[str, str]) -> None:
    """Merge env into every (main) container of a pod template; values the
    user already set win (parity with the reference appending EnvVars —
    first occurrence wins in kubelet)."""
    for c in pod_template.spec.containers:
        for k, v in env.items():
            c.env.setdefault(k, v)


def global_rank(
    replica_specs: Dict[str, ReplicaSpec],
    order: list,
    coordinator_rtype: str,
    rtype: str,
    index: int,
) -> int:
    """Globally-unique process id with the coordinator replica pinned to 0.

    jax.distributed requires process 0 to host the coordination service at
    the advertised address, so the rank ordering puts the coordinator's
    replica type first, then the remaining types in the controller's
    reconcile order.
    """
    ordered = [coordinator_rtype] + [
        t for t in order if t != coordinator_rtype and t in replica_specs
    ]
    rank = 0
    for t in ordered:
        spec = replica_specs.get(t)
        if spec is None:
            continue
        if t == rtype:
            return rank + int(index)
        rank += int(spec.replicas or 0)
    return rank + int(index)


def inject_coordinator_env(
    job, pod_template, rtype: str, index: int,
    replica_specs: Dict[str, ReplicaSpec],
    coordinator_rtype: str,
    order: list,
) -> None:
    """The ONE rendezvous scheme for TPU-native workloads: the coordinator
    replica's index-0 pod hosts the JAX coordination service; every process
    gets its address, the world size, and a unique process id where id 0 IS
    the pod at that address."""
    addr = f"{service_dns(job, coordinator_rtype, 0)}:{COORDINATOR_PORT}"
    add_env(
        pod_template,
        {
            ENV_COORDINATOR_ADDRESS: addr,
            ENV_NUM_PROCESSES: str(get_total_replicas(replica_specs)),
            ENV_PROCESS_ID: str(
                global_rank(replica_specs, order, coordinator_rtype, rtype, index)
            ),
        },
    )
