"""JAXJob — the flagship first-class TPU workload (net-new).

Added via the reference's documented extension path
(ref docs/how-to-add-a-custom-workload.md:1-110): a new kind + controller
registered with the shared engine. Design (SURVEY.md §7 step 4):
  * replica types: Worker (SPMD ranks; worker-0 hosts the coordination
    service). No PS, no chief — JAX is single-program multi-data;
  * spec.mesh declares named axes ("data", "fsdp", "tensor", "context",
    "expert") the runtime materializes as a jax.sharding.Mesh over the
    slice (parallel/mesh.py);
  * spec.checkpoint: Orbax checkpoint dir + save interval — first-class
    because TPU preemptions make resume mandatory (SURVEY.md §5);
  * SetClusterSpec injects ONLY the coordination-service env (one rendezvous
    scheme instead of the reference's four) plus the mesh/checkpoint config;
  * default restart policy ExitCode: TPU preemptions exit retryable
    (utils/exit_codes.py), XLA compile errors permanent.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import (
    LABEL_RL_ROLE,
    LABEL_SERVING_ROLE,
    LABEL_SLICE_ID,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    slice_group,
)
from kubedl_tpu.api.job import BaseJob
from kubedl_tpu.controllers.base import BaseWorkloadController
from kubedl_tpu.controllers.registry import register_workload
from kubedl_tpu.workloads import common

KIND = "JAXJob"
API_VERSION = "kubedl-tpu.io/v1alpha1"

REPLICA_WORKER = str(ReplicaType.WORKER.value)

_CANONICAL = {"worker": REPLICA_WORKER}


def _job_transport_token(job) -> str:
    """Per-job transport auth token, derived sha256 from the job UID so
    every pod of the gang — across operator restarts — gets the SAME
    secret and no other job can forge it (the UID is an unguessable
    uuid4 internal to the cluster; a production deployment can still pin
    its own token via a mounted Secret, which wins over this default).
    Empty when the job has no UID yet."""
    if not job.metadata.uid:
        return ""
    import hashlib

    return hashlib.sha256(
        f"kubedl-transport:{job.metadata.uid}".encode()).hexdigest()


@dataclass
class MeshSpec:
    """Named mesh axes; sizes multiply to the process*local-device count.
    A size of -1 means "fill with whatever devices remain" (like a reshape)."""

    data: int = 1
    fsdp: int = 1
    stage: int = 1  # pipeline stages (spec.pipeline picks the schedule)
    tensor: int = 1
    context: int = 1
    expert: int = 1

    def axis_dict(self) -> Dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "stage": self.stage,
            "tensor": self.tensor,
            "context": self.context,
            "expert": self.expert,
        }

    def encode(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.axis_dict().items())

    def encode_sparse(self) -> str:
        """Only the non-trivial axes — the KUBEDL_DCN_MESH wire form, where
        unset axes default to 1 (parallel/mesh.py parse_dcn_mesh_env)."""
        return ",".join(f"{k}={v}" for k, v in self.axis_dict().items() if v != 1)

    def product(self) -> int:
        p = 1
        for v in self.axis_dict().values():
            p *= v
        return p


@dataclass
class CheckpointSpec:
    path: str = ""
    save_interval_steps: int = 0
    keep: int = 3
    restore: bool = True


@dataclass
class ElasticSpec:
    """Elastic-resize behavior beyond the scheduler's shape ladder
    (schedulingPolicy.tpuSliceFallbacks declares the shapes).

    liveReshard opts the gang into the live resharding plane
    (docs/scheduling.md "Live resharding"): scheduler resizes and
    dead-slice shrinks quiesce the gang at a step boundary and reshard
    params + optimizer state onto the new mesh (parallel/reshard.py)
    instead of the checkpoint-then-evict round trip; every failure falls
    back CLOSED to checkpoint restore, which is why spec.checkpoint is
    required."""

    live_reshard: bool = False
    # quiesce budget for the staged (multi-process) lane: how long worker
    # 0 waits for every pod's shard stage before aborting to checkpoint
    quiesce_timeout_s: float = 30.0


@dataclass
class ServingSpec:
    """Disaggregated serving fleet (kubedl_tpu/serving/): the Worker
    replicas split into prefill and decode ROLES by index — workers
    [0, prefillReplicas) prefill, the rest decode — behind the router
    (serving/router.py; server.py exposes fleet state + drain). The
    paged-KV knobs are injected per pod as KUBEDL_SERVING_* env."""

    prefill_replicas: int = 1
    decode_replicas: int = 1
    slots: int = 8  # concurrent decode streams per decode pod
    max_len: int = 1024
    block_size: int = 16  # paged-KV block (rows per block)
    kv_blocks: int = 0  # 0 = equal memory to slots * max_len
    share_prefixes: bool = True
    # routing policies (router.py): the defaults are the only ones
    # implemented; the fields exist so manifests state intent explicitly
    prefill_router: str = "shortest-queue"
    decode_router: str = "least-blocks"


@dataclass
class PipelineSpec:
    """Pipeline parallelism (docs/pipeline.md). Intra-slice, the trainer
    runs the stacked-layer schedule over the mesh's "stage" axis
    (parallel/pipeline.py): "gpipe" (the parity oracle) or "1f1b" (the
    interleaved circular schedule; `interleave` virtual stages per rank
    cut the fill/drain bubble ~1/interleave). With `mpmd: true` the job
    instead becomes `stages` SEPARATE programs, one per slice
    (spec.numSlices == stages), joined by the serialized DCN activation
    boundary (train/pipeline_runtime.py) — the shape that trains a model
    bigger than one slice's HBM. `stageSlices` optionally names a
    different slice type PER STAGE (heterogeneous gang; admitted
    all-or-nothing, gavel-priced); `layers` optionally declares the
    model's layer count so divisibility is rejected at submit."""

    stages: int = 1
    microbatches: int = 0  # 0 = stages (the minimum that fills the pipe)
    interleave: int = 1
    schedule: str = "1f1b"  # gpipe | 1f1b (intra-slice loop)
    mpmd: bool = False
    layers: int = 0  # 0 = unknown at submit (runtime re-validates)
    stage_slices: List[str] = field(default_factory=list)

    def resolved_microbatches(self) -> int:
        return self.microbatches or self.stages


@dataclass
class RLSpec:
    """Disaggregated actor/learner RL fleet (kubedl_tpu/rl/, docs/rl.md):
    the Worker replicas split into actor and learner ROLES by index —
    workers [0, actorReplicas) are actors, the rest the learner — joined
    by the trajectory queue and versioned weight broadcast over the
    transport plane. ``maxWeightLag`` is the off-policy staleness bound:
    the learner drops trajectories sampled more than that many weight
    versions ago (counted), and actors park rather than generate
    guaranteed-stale work. ``actorSlice``/``learnerSlice`` name the
    per-role slice shapes of a mixed-role gang (admitted all-or-nothing
    — an actor fleet without a learner shields nothing; requires
    spec.numSlices == actorReplicas + learnerReplicas)."""

    actor_replicas: int = 1
    learner_replicas: int = 1
    group_size: int = 8          # G completions sampled per prompt
    max_weight_lag: int = 1      # off-policy staleness bound (versions)
    prompts_per_step: int = 4    # trajectory groups per learner update
    max_new_tokens: int = 32
    temperature: float = 1.0
    reward: str = "token-match"  # token-match | length | module.path:fn
    reward_token: int = 5
    target_len: int = 16
    eos_id: int = -1
    broadcast_interval: int = 1  # learner steps between weight publishes
    rollout_engine: str = "decode"  # decode | serving (paged-KV reuse)
    actor_slice: str = ""        # per-role gang shapes (both or neither)
    learner_slice: str = ""


@dataclass
class JAXJobSpec:
    replica_specs: Dict[str, ReplicaSpec] = field(
        default_factory=dict, metadata={"name": "jaxReplicaSpecs"}
    )
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    mesh: Optional[MeshSpec] = None
    checkpoint: Optional[CheckpointSpec] = None
    # Multislice: the job spans num_slices TPU slices joined by DCN.
    # `mesh` stays the per-slice (ICI) axes; `dcn_mesh` declares which
    # axes span slices (default data=num_slices — the standard recipe:
    # data parallel over DCN, fsdp/tensor/context inside each slice).
    # Workers divide evenly into slices by index; the gang admitter
    # reserves num_slices whole slices atomically or nothing.
    num_slices: int = 1
    dcn_mesh: Optional[MeshSpec] = None
    # Persistent XLA compile cache dir (a mounted volume / GCS path):
    # after a preemption the restarted slice replays compiles from cache
    # instead of paying minutes of XLA again. Injected as JAX's native
    # JAX_COMPILATION_CACHE_DIR (serde camelCases the wire name).
    compilation_cache_dir: str = ""
    # Disaggregated serving mode: Worker replicas become a routed
    # prefill/decode fleet instead of an SPMD training gang.
    serving: Optional[ServingSpec] = None
    # Elastic behavior (live resharding opt-in); the admissible shapes
    # themselves live in runPolicy.schedulingPolicy.tpuSliceFallbacks.
    elastic: Optional[ElasticSpec] = None
    # Pipeline parallelism: intra-slice schedule knobs, or (mpmd) the
    # cross-slice multi-program mode where each stage owns a slice.
    pipeline: Optional[PipelineSpec] = None
    # Actor/learner RL fleet: Worker replicas become rollout actors plus
    # a learner joined by trajectory queue + weight broadcast.
    rl: Optional[RLSpec] = None


@dataclass
class JAXJob(BaseJob):
    spec: JAXJobSpec = field(default_factory=JAXJobSpec)
    kind: str = KIND


class JAXJobController(BaseWorkloadController):
    kind = KIND
    api_version = API_VERSION
    default_container_name = "jax"
    default_port_name = "jaxjob-port"
    default_port = common.COORDINATOR_PORT

    replica_key_map = _CANONICAL

    # elastic resize opt-in (api/validation.py): the trainer restores
    # shape-agnostically from Orbax checkpoints, so the capacity
    # scheduler may re-admit the gang at a declared fallback shape
    supports_elastic = True

    def job_type(self):
        return JAXJob

    def replica_specs(self, job):
        return job.spec.replica_specs

    def set_defaults(self, job) -> None:
        super().set_defaults(job)
        if job.spec.run_policy.backoff_limit is None:
            # preemptions are routine on TPU; retry generously
            job.spec.run_policy.backoff_limit = 10

    def default_restart_policy(self, rtype: str) -> RestartPolicy:
        return RestartPolicy.EXIT_CODE

    def restart_whole_gang(self, job, replicas) -> bool:
        """Multi-worker SPMD jobs restart as a slice: every rank blocks in
        jax.distributed.initialize at startup, so a lone restarted worker
        would hang against peers that are mid-run.

        Serving fleets are the exception: pods are independent routed
        endpoints, not SPMD ranks — one dead decode pod must restart
        ALONE while the router fails its streams over, never take the
        whole fleet down with it (that would turn one pod crash into a
        full-fleet outage, the exact failure-isolation the
        disaggregated plane exists to prevent)."""
        if getattr(getattr(job, "spec", None), "serving", None) is not None:
            return False
        return sum(int(s.replicas or 0) for s in replicas.values()) > 1

    @property
    def master_types(self) -> List[str]:
        return []

    def reconcile_orders(self):
        return [ReplicaType.WORKER]

    def validate_job(self, job) -> List[str]:
        errs = []
        ns = int(job.spec.num_slices or 1)
        workers = int(
            (job.spec.replica_specs.get(REPLICA_WORKER) or ReplicaSpec()).replicas
            or 0
        )
        if ns < 1:
            errs.append(f"spec.numSlices must be >=1, got {ns}")
        elif ns > 1:
            if workers % ns:
                errs.append(
                    f"spec.numSlices={ns} must divide the Worker replica "
                    f"count {workers} (each slice gets an equal worker group)"
                )
            if job.spec.dcn_mesh is not None and job.spec.dcn_mesh.product() != ns:
                errs.append(
                    f"spec.dcnMesh axes multiply to "
                    f"{job.spec.dcn_mesh.product()}, must equal "
                    f"spec.numSlices={ns}"
                )
        elif job.spec.dcn_mesh is not None:
            errs.append("spec.dcnMesh requires spec.numSlices > 1")
        srv = job.spec.serving
        if srv is not None:
            pf, dc = int(srv.prefill_replicas), int(srv.decode_replicas)
            if pf < 1 or dc < 1:
                errs.append(
                    f"spec.serving needs >= 1 prefill and >= 1 decode "
                    f"replica, got {pf}/{dc}")
            elif pf + dc != workers:
                errs.append(
                    f"spec.serving prefillReplicas {pf} + decodeReplicas "
                    f"{dc} must equal the Worker replica count {workers} "
                    f"(roles are assigned by worker index)")
            if ns > 1:
                errs.append(
                    "spec.serving is incompatible with spec.numSlices > 1 "
                    "(serving pods are independent endpoints, not a "
                    "multislice SPMD gang)")
            if (srv.block_size < 1 or srv.max_len < 1
                    or srv.max_len % srv.block_size):
                errs.append(
                    f"spec.serving maxLen {srv.max_len} must be a positive "
                    f"multiple of blockSize {srv.block_size} (>= 1)")
            if srv.slots < 1:
                errs.append(
                    f"spec.serving slots must be >= 1, got {srv.slots}")
            if srv.kv_blocks != 0 and srv.kv_blocks < 2:
                errs.append(
                    f"spec.serving kvBlocks must be 0 (auto-size to the "
                    f"contiguous cache's memory) or >= 2 (one block is "
                    f"the reserved trash block), got {srv.kv_blocks}")
            if srv.prefill_router != "shortest-queue":
                errs.append(
                    f"unknown spec.serving prefillRouter "
                    f"{srv.prefill_router!r} (supported: shortest-queue)")
            if srv.decode_router != "least-blocks":
                errs.append(
                    f"unknown spec.serving decodeRouter "
                    f"{srv.decode_router!r} (supported: least-blocks)")
        sched = (job.spec.run_policy.scheduling_policy
                 if job.spec.run_policy else None)
        pipe = job.spec.pipeline
        if pipe is not None:
            from kubedl_tpu.api.validation import validate_pipeline_shapes
            from kubedl_tpu.executor.tpu_topology import parse_slice_type

            errs.extend(validate_pipeline_shapes(
                int(pipe.stages), pipe.resolved_microbatches(),
                int(pipe.interleave),
                n_layers=int(pipe.layers) or None,
                schedule=pipe.schedule))
            if pipe.mpmd:
                if ns <= 1:
                    errs.append(
                        "spec.pipeline.mpmd requires spec.numSlices > 1 "
                        "(each stage program owns its own slice — one "
                        "slice has nothing to span)")
                elif ns != int(pipe.stages):
                    errs.append(
                        f"spec.pipeline.mpmd needs spec.numSlices "
                        f"({ns}) == spec.pipeline.stages ({pipe.stages}) "
                        f"(one stage program per slice)")
                if job.spec.dcn_mesh is not None:
                    errs.append(
                        "spec.pipeline.mpmd is incompatible with "
                        "spec.dcnMesh (the stage dimension IS the "
                        "cross-slice dimension; there is no Megascale "
                        "mesh to declare)")
                if int(pipe.interleave) > 1:
                    errs.append(
                        "spec.pipeline.mpmd supports interleave=1 only "
                        "(virtual stages are the intra-slice schedule's "
                        "optimization; the MPMD runtime runs plain 1F1B)")
                if srv is not None:
                    errs.append(
                        "spec.pipeline.mpmd is incompatible with "
                        "spec.serving")
                if sched is not None and sched.tpu_slice_fallbacks:
                    errs.append(
                        "spec.pipeline.mpmd is incompatible with "
                        "schedulingPolicy.tpuSliceFallbacks (per-stage "
                        "programs cannot resize through the elastic "
                        "ladder; declare per-stage shapes in "
                        "spec.pipeline.stageSlices instead)")
                if job.spec.checkpoint is None or not job.spec.checkpoint.path:
                    errs.append(
                        "spec.pipeline.mpmd requires spec.checkpoint "
                        "(the stage boundary channel rides the shared "
                        "checkpoint volume on the local executor)")
            elif int(pipe.stages) > 1:
                mesh_stage = job.spec.mesh.stage if job.spec.mesh else 1
                if int(mesh_stage) != int(pipe.stages):
                    errs.append(
                        f"spec.pipeline.stages={pipe.stages} without mpmd "
                        f"needs spec.mesh.stage == stages (the SPMD "
                        f"schedule runs over the mesh's stage axis), got "
                        f"{mesh_stage}")
            if pipe.stage_slices:
                if not pipe.mpmd:
                    errs.append(
                        "spec.pipeline.stageSlices requires "
                        "spec.pipeline.mpmd (per-stage slice shapes only "
                        "make sense when each stage owns a slice)")
                elif len(pipe.stage_slices) != int(pipe.stages):
                    errs.append(
                        f"spec.pipeline.stageSlices has "
                        f"{len(pipe.stage_slices)} entries for "
                        f"{pipe.stages} stages")
                for alt in pipe.stage_slices:
                    try:
                        parse_slice_type(alt)
                    except ValueError as e:
                        errs.append(f"spec.pipeline.stageSlices: {e}")
        el = job.spec.elastic
        if el is not None and el.live_reshard:
            if job.spec.checkpoint is None or not job.spec.checkpoint.path:
                errs.append(
                    "spec.elastic.liveReshard requires spec.checkpoint "
                    "(the reshard ladder falls back CLOSED to checkpoint "
                    "restore; without one a failed reshard would lose all "
                    "progress)")
            if sched is None or not sched.tpu_slice_fallbacks:
                errs.append(
                    "spec.elastic.liveReshard requires schedulingPolicy."
                    "tpuSliceFallbacks (the fallback shapes are what the "
                    "gang reshards between)")
            if ns > 1:
                errs.append(
                    "spec.elastic.liveReshard is incompatible with "
                    "spec.numSlices > 1 (multislice gangs resize through "
                    "checkpoint restore today)")
            if srv is not None:
                errs.append(
                    "spec.elastic.liveReshard does not apply to "
                    "spec.serving fleets (serving pods are independent "
                    "endpoints; drain them through the router instead)")
            if float(el.quiesce_timeout_s) <= 0:
                errs.append(
                    f"spec.elastic.quiesceTimeoutS must be > 0, got "
                    f"{el.quiesce_timeout_s}")
        rl = job.spec.rl
        if rl is not None:
            from kubedl_tpu.api.validation import validate_rl_shapes
            from kubedl_tpu.executor.tpu_topology import parse_slice_type

            errs.extend(validate_rl_shapes(
                int(rl.actor_replicas), int(rl.learner_replicas),
                int(rl.group_size), int(rl.max_weight_lag),
                prompts_per_step=int(rl.prompts_per_step),
                max_new_tokens=int(rl.max_new_tokens),
                temperature=float(rl.temperature),
                broadcast_interval=int(rl.broadcast_interval),
                reward=str(rl.reward), eos_id=int(rl.eos_id),
                rollout_engine=str(rl.rollout_engine)))
            fleet = int(rl.actor_replicas) + int(rl.learner_replicas)
            if fleet != workers:
                errs.append(
                    f"spec.rl actorReplicas {rl.actor_replicas} + "
                    f"learnerReplicas {rl.learner_replicas} must equal "
                    f"the Worker replica count {workers} (roles are "
                    f"assigned by worker index, actors first)")
            if bool(rl.actor_slice) != bool(rl.learner_slice):
                errs.append(
                    "spec.rl actorSlice and learnerSlice must be set "
                    "together (a mixed-role gang needs BOTH role shapes "
                    "to admit all-or-nothing) or both left empty")
            elif rl.actor_slice:
                for field_name, alt in (("actorSlice", rl.actor_slice),
                                        ("learnerSlice", rl.learner_slice)):
                    try:
                        parse_slice_type(alt)
                    except ValueError as e:
                        errs.append(f"spec.rl.{field_name}: {e}")
                if ns != fleet:
                    errs.append(
                        f"spec.rl with role slices needs spec.numSlices "
                        f"({ns}) == actorReplicas + learnerReplicas "
                        f"({fleet}) — each fleet pod owns one slice")
            elif ns != 1:
                errs.append(
                    f"spec.rl without actorSlice/learnerSlice requires "
                    f"spec.numSlices == 1 (got {ns}): a multi-slice RL "
                    f"gang must declare its per-role shapes")
            if job.spec.dcn_mesh is not None:
                errs.append(
                    "spec.rl is incompatible with spec.dcnMesh (actor "
                    "and learner pods are SEPARATE programs joined by "
                    "the trajectory/broadcast channels, not one SPMD "
                    "program over a DCN mesh)")
            if srv is not None:
                errs.append("spec.rl is incompatible with spec.serving "
                            "(the fleet runs its own rollout engines)")
            if pipe is not None:
                errs.append("spec.rl is incompatible with spec.pipeline")
            if el is not None and el.live_reshard:
                errs.append(
                    "spec.rl is incompatible with spec.elastic."
                    "liveReshard (fleet pods are separate programs; "
                    "there is no single SPMD state to reshard)")
            if sched is not None and sched.tpu_slice_fallbacks:
                errs.append(
                    "spec.rl is incompatible with schedulingPolicy."
                    "tpuSliceFallbacks (a mixed-role gang cannot resize "
                    "through the elastic ladder; size the roles via "
                    "spec.rl.actorSlice/learnerSlice instead)")
            if job.spec.checkpoint is None or not job.spec.checkpoint.path:
                errs.append(
                    "spec.rl requires spec.checkpoint (the trajectory "
                    "queue and weight broadcast ride the shared "
                    "checkpoint volume on the local executor, and the "
                    "learner checkpoints the policy there)")
        if sched is not None and sched.tpu_slice_fallbacks and (
            job.spec.checkpoint is None or not job.spec.checkpoint.path
        ):
            # shape sanity is validated for every kind in validate_common;
            # the checkpoint requirement is the JAX-specific half —
            # resizes restart the trainer through checkpoint-restore
            errs.append(
                "schedulingPolicy.tpuSliceFallbacks requires "
                "spec.checkpoint (elastic resize restarts the job "
                "through checkpoint-restore; without one every resize "
                "would silently lose all training progress)"
            )
        return errs

    def set_cluster_spec(self, job, pod_template, rtype: str, index: int) -> None:
        env = {}
        if job.spec.mesh is not None:
            env["KUBEDL_MESH"] = job.spec.mesh.encode()
        ns = int(job.spec.num_slices or 1)
        pipe = job.spec.pipeline
        rl = job.spec.rl
        # validation requires numSlices > 1 for mpmd; the guard keeps an
        # unvalidated job from hitting the slice-group math below
        mpmd = pipe is not None and pipe.mpmd and ns > 1
        if ns > 1:
            # Multislice: per-slice worker groups by index; libtpu's
            # Megascale DCN transport bootstraps from MEGASCALE_* the way
            # single-slice jobs bootstrap from the coordination service.
            # An MPMD pipeline job — and an RL fleet — skips Megascale
            # entirely: its slices are SEPARATE programs chained by the
            # activation boundary (or the trajectory/broadcast
            # channels), not one SPMD program over a DCN mesh.
            workers = int(
                (job.spec.replica_specs.get(REPLICA_WORKER) or ReplicaSpec())
                .replicas or 0
            )
            slice_id, _, _ = slice_group(workers, ns, index)
            env["KUBEDL_NUM_SLICES"] = str(ns)
            env["KUBEDL_SLICE_ID"] = str(slice_id)
            if not mpmd and rl is None:
                dcn = job.spec.dcn_mesh
                dcn_encoded = (dcn.encode_sparse() if dcn is not None
                               else f"data={ns}")
                env["KUBEDL_DCN_MESH"] = dcn_encoded
                env["MEGASCALE_NUM_SLICES"] = str(ns)
                env["MEGASCALE_SLICE_ID"] = str(slice_id)
                env["MEGASCALE_COORDINATOR_ADDRESS"] = (
                    f"{common.service_dns(job, REPLICA_WORKER, 0)}"
                    f":{common.MEGASCALE_PORT}"
                )
            pod_template.metadata.labels[LABEL_SLICE_ID] = str(slice_id)
        if pipe is not None:
            env["KUBEDL_PP_STAGES"] = str(pipe.stages)
            env["KUBEDL_PP_MICROBATCHES"] = str(pipe.resolved_microbatches())
            env["KUBEDL_PP_INTERLEAVE"] = str(pipe.interleave)
            env["KUBEDL_PP_SCHEDULE"] = pipe.schedule
            if mpmd:
                # validation guarantees ns > 1 here, so the multislice
                # block above already computed workers + this pod's
                # slice id — which IS its stage (one stage per slice)
                from kubedl_tpu.executor.tpu_topology import (
                    pipeline_neighbor_env,
                )

                stage = slice_id
                per_stage = workers // max(ns, 1)

                def stage_addr(s: int) -> str:
                    return (f"{common.service_dns(job, REPLICA_WORKER, s * per_stage)}"
                            f":{common.PIPELINE_PORT}")

                env["KUBEDL_PP_MPMD"] = "1"
                env.update(pipeline_neighbor_env(
                    stage, ns,
                    prev_addr=stage_addr(stage - 1) if stage > 0 else "",
                    next_addr=(stage_addr(stage + 1)
                               if stage < ns - 1 else "")))
                # socket-plane listen endpoint (docs/transport.md): the
                # neighbor addrs above dial this port, so the stage's
                # plane must bind it when KUBEDL_TRANSPORT=socket (kube
                # mode; the local executor defaults to the dir lane)
                env["KUBEDL_TRANSPORT_BIND"] = (
                    f"0.0.0.0:{common.PIPELINE_PORT}")
                # per-job auth token (see _job_transport_token)
                token = _job_transport_token(job)
                if token:
                    env["KUBEDL_TRANSPORT_TOKEN"] = token
                ckpt_path = (job.spec.checkpoint.path
                             if job.spec.checkpoint else "")
                if ckpt_path:
                    # local-executor DCN analog: the boundary channel is
                    # a shared dir on the (already required) checkpoint
                    # volume — same discipline as the reshard staging dir
                    env["KUBEDL_PP_BOUNDARY_DIR"] = os.path.join(
                        ckpt_path, ".pipeline")
        ckpt = job.spec.checkpoint
        if ckpt is not None and ckpt.path:
            env["KUBEDL_CHECKPOINT_PATH"] = ckpt.path
            env["KUBEDL_CHECKPOINT_INTERVAL"] = str(ckpt.save_interval_steps)
            env["KUBEDL_CHECKPOINT_KEEP"] = str(ckpt.keep)
            env["KUBEDL_CHECKPOINT_RESTORE"] = "1" if ckpt.restore else "0"
        el = job.spec.elastic
        if el is not None and el.live_reshard and ckpt is not None and ckpt.path:
            # live-reshard opt-in: control-channel polling on, plus the
            # gang-shared staging dir for the multi-process lane (rides
            # the checkpoint volume — already required + shared)
            env["KUBEDL_LIVE_RESHARD"] = "1"
            env["KUBEDL_RESHARD_DIR"] = os.path.join(ckpt.path, ".reshard")
            env["KUBEDL_RESHARD_QUIESCE_S"] = str(el.quiesce_timeout_s)
        if job.spec.compilation_cache_dir:
            # JAX's own min-compile-time default (1s) already skips
            # sub-second compiles — no need to override it here
            env["JAX_COMPILATION_CACHE_DIR"] = job.spec.compilation_cache_dir
        srv = job.spec.serving
        if srv is not None:
            role = ("prefill" if index < int(srv.prefill_replicas)
                    else "decode")
            env["KUBEDL_SERVING_ROLE"] = role
            env["KUBEDL_SERVING_SLOTS"] = str(srv.slots)
            env["KUBEDL_SERVING_MAX_LEN"] = str(srv.max_len)
            env["KUBEDL_SERVING_BLOCK_SIZE"] = str(srv.block_size)
            env["KUBEDL_SERVING_KV_BLOCKS"] = str(srv.kv_blocks)
            env["KUBEDL_SERVING_SHARE_PREFIXES"] = (
                "1" if srv.share_prefixes else "0")
            pod_template.metadata.labels[LABEL_SERVING_ROLE] = role
        if rl is not None:
            from kubedl_tpu.executor.tpu_topology import rl_fleet_env

            n_act = int(rl.actor_replicas)
            role = "actor" if index < n_act else "learner"

            def rl_addr(i: int) -> str:
                return (f"{common.service_dns(job, REPLICA_WORKER, i)}"
                        f":{common.RL_PORT}")

            env.update(rl_fleet_env(
                role, index, n_act,
                learner_addr=rl_addr(n_act),
                actor_addrs=",".join(rl_addr(i) for i in range(n_act))))
            env["KUBEDL_RL_GROUP_SIZE"] = str(rl.group_size)
            env["KUBEDL_RL_PROMPTS_PER_STEP"] = str(rl.prompts_per_step)
            env["KUBEDL_RL_MAX_NEW_TOKENS"] = str(rl.max_new_tokens)
            env["KUBEDL_RL_TEMPERATURE"] = str(rl.temperature)
            env["KUBEDL_RL_MAX_WEIGHT_LAG"] = str(rl.max_weight_lag)
            env["KUBEDL_RL_BROADCAST_INTERVAL"] = str(rl.broadcast_interval)
            env["KUBEDL_RL_REWARD"] = rl.reward
            env["KUBEDL_RL_REWARD_TOKEN"] = str(rl.reward_token)
            env["KUBEDL_RL_TARGET_LEN"] = str(rl.target_len)
            env["KUBEDL_RL_EOS_ID"] = str(rl.eos_id)
            env["KUBEDL_RL_ENGINE"] = rl.rollout_engine
            # socket-plane listen endpoint (docs/transport.md): the peer
            # addrs above dial this port, so every fleet pod's plane
            # binds it when KUBEDL_TRANSPORT=socket (kube mode; the
            # local executor defaults to the dir lane)
            env["KUBEDL_TRANSPORT_BIND"] = f"0.0.0.0:{common.RL_PORT}"
            token = _job_transport_token(job)
            if token:
                env["KUBEDL_TRANSPORT_TOKEN"] = token
            ckpt_path = (job.spec.checkpoint.path
                         if job.spec.checkpoint else "")
            if ckpt_path:
                # local-executor DCN analog: the trajectory queue and
                # weight broadcast are shared dirs on the (already
                # required) checkpoint volume — the KUBEDL_PP_BOUNDARY_DIR
                # discipline
                env["KUBEDL_RL_QUEUE_DIR"] = os.path.join(
                    ckpt_path, ".rl")
            pod_template.metadata.labels[LABEL_RL_ROLE] = role
        common.add_env(pod_template, env)
        common.inject_coordinator_env(
            job, pod_template, rtype, index, job.spec.replica_specs,
            REPLICA_WORKER, [str(rt.value) for rt in self.reconcile_orders()],
        )


register_workload("jax", JAXJobController)
