"""Operator — the process entrypoint wiring (ref main.go:48-115).

Assembles: object store (L0-equivalent), controller manager, per-workload
reconcilers (registered via the workload registry, gated like the reference's
workloadgate), TPU-slice gang admission, the local pod executor, metrics
registry, and optional storage persistence. Usage:

    op = Operator(OperatorConfig(enable_gang_scheduling=True,
                                 tpu_slices=["v5e-8", "v5p-32"]))
    op.register_all()       # every known workload (TF/PyTorch/XGB/XDL/JAX)
    op.start()
    job = op.apply(manifest_dict)           # like kubectl apply
    op.wait_for_condition(job, "Succeeded")
    op.stop()
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.common import JobConditionType, has_condition
from kubedl_tpu.controllers.engine import EngineConfig, JobReconciler
from kubedl_tpu.core.events import EventRecorder
from kubedl_tpu.core.manager import Manager
from kubedl_tpu.core.store import NotFound, ObjectStore
from kubedl_tpu.executor.local import LocalPodExecutor
from kubedl_tpu.gang.interface import GangRegistry
from kubedl_tpu.gang.slice_admitter import TPUSliceAdmitter
from kubedl_tpu.metrics.job_metrics import MetricsRegistry
from kubedl_tpu.metrics.runtime_metrics import RuntimeMetrics
from kubedl_tpu.api.validation import validate
from kubedl_tpu.core.leader import DEFAULT_LEASE_PATH, FileLeaseElector, read_epoch
from kubedl_tpu.utils.serde import from_dict

log = logging.getLogger("kubedl_tpu.operator")


@dataclass
class OperatorConfig:
    # flag parity with ref main.go:54-66 / docs/startup_flags.md
    max_reconciles: int = 1
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = "tpu-slice"
    # TPU pool available to the executor, e.g. ["v5e-8", "v5p-32"]
    tpu_slices: List[str] = field(default_factory=list)
    # Capacity scheduler (sched/capacity.py): "" keeps the admitter's
    # built-in (priority desc, FIFO) queue; naming a policy (fifo |
    # priority | fair_share | gavel) enables tenant fair-share admission,
    # active preemption, and elastic slice resizing. Implies gang
    # scheduling. See docs/scheduling.md.
    scheduler_policy: str = ""
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    tenant_caps: Dict[str, int] = field(default_factory=dict)
    enable_preemption: bool = True
    # tick cadence: each tick takes the admitter lock several times
    # (snapshots, kicks, demand probes), so pace it in human time;
    # tests override for fast convergence
    scheduler_interval: float = 0.5
    preemption_backoff: float = 0.5
    enable_elastic: bool = True
    elastic_shrink_delay: float = 0.5
    elastic_grow_delay: float = 2.0
    # flight recorder root (docs/observability.md): per-job trace dirs
    # land under it ("" = a fresh temp dir). Control-plane spans and the
    # executor-injected KUBEDL_TRACE_DIR both resolve against this root.
    trace_dir: str = ""
    # workload gate expression, ref pkg/util/workloadgate: "*", "tf,pytorch", "*,-xdl"
    workloads: str = "*"
    cluster_domain: str = ""
    run_executor: bool = True
    # persistence flags, ref persist_controller.go:30-74 (--object-storage /
    # --event-storage + REGION env); backend names resolve via the storage
    # registry ("sqlite" built in). Empty string disables.
    object_storage: str = ""
    event_storage: str = ""
    storage_db_path: str = ":memory:"
    region: str = field(default_factory=lambda: os.environ.get("REGION", ""))
    # HA: single active operator via a lease (ref main.go:56 --enable-leader-
    # election, default true there; off by default here because embedded/test
    # operators are single-instance — the CLI `operator` command enables it)
    enable_leader_election: bool = False
    leader_lease_path: str = DEFAULT_LEASE_PATH
    # kube mode: coordination.k8s.io Lease timing (client-go-ish defaults)
    leader_lease_duration: float = 15.0
    leader_renew_period: float = 5.0
    leader_retry_period: float = 2.0
    # Durable control plane (docs/ha.md): write-ahead grant/drain
    # journal — every admitter transition is fsync'd to
    # <journal_dir>/grant.journal BEFORE the in-memory commit and
    # replayed on the next start, so a crashed operator never re-grants
    # a slice whose previous pod still runs. "" disables (embedded/test
    # operators); the CLI `operator` command defaults it under the data
    # root (core/leader.py data_root()).
    journal_dir: str = ""
    # Journal compaction threshold in bytes: when the on-disk journal
    # grows past this, the next admitter reservation pass snapshots the
    # effective state and truncates (tmp+rename, epoch-stamped). 0
    # disables compaction (the PR 18 behavior: the journal grows until
    # job TTL cleanup).
    journal_compact_bytes: int = 1024 * 1024
    # Fleet history store (docs/ha.md): trace spans + goodput +
    # lifecycle markers persisted past job TTL, queryable via
    # GET /history/<ns>/<job> and `kubedl-tpu history`. "" disables.
    history_dir: str = ""
    # History retention (PR 18's named leftover): prune history.jsonl
    # records older than max-age seconds and rewrite the file down
    # when it grows past max-bytes (tmp+replace, epoch-stamped prune
    # marker). 0 disables that bound; both 0 = keep forever.
    history_retention_max_age_s: float = 0.0
    history_retention_max_bytes: int = 0
    # Kubernetes mode: reconcile real Pod/Service objects on a cluster
    # through the kube-apiserver instead of the in-process store + local
    # executor (ref main.go:70-75 manager-over-client-go). "in-cluster"
    # resolves the service-account config; otherwise an apiserver URL.
    kube_api_url: str = ""
    kube_namespace: str = "default"


class Operator:
    def __init__(self, config: Optional[OperatorConfig] = None, store=None) -> None:
        self.config = config or OperatorConfig()
        self._owns_store = store is None  # covers BOTH internal branches
        if store is not None:
            self.store = store
        elif self.config.kube_api_url:
            from kubedl_tpu.k8s import KubeClient, KubeObjectStore

            url = self.config.kube_api_url
            client = (
                KubeClient.resolve() if url == "in-cluster" else KubeClient.resolve(url)
            )
            self.store = KubeObjectStore(client, namespace=self.config.kube_namespace)
        else:
            self.store = ObjectStore()
        if self.kube_mode:
            # the cluster's kubelets run pods; no local executor
            self.config.run_executor = False
        self.runtime_metrics = RuntimeMetrics()
        # pipeline-schedule health (kubedl_pipeline_*): the in-process
        # MPMD lane feeds the module singleton; register unconditionally
        # (renders nothing until a pipeline job reports)
        from kubedl_tpu.metrics.runtime_metrics import pipeline_metrics

        self.runtime_metrics.register_pipeline(pipeline_metrics.snapshot)
        # transport-plane counters (kubedl_transport_*): every plane in
        # the process folds into the module singleton; register
        # unconditionally (renders zeros until a plane carries traffic)
        from kubedl_tpu.transport.metrics import transport_metrics

        self.runtime_metrics.register_transport(transport_metrics.snapshot)
        # RL-fleet health (kubedl_rl_*): actor/learner runtimes feed the
        # module singleton; register unconditionally (renders nothing
        # until an RL job reports)
        from kubedl_tpu.rl.metrics import rl_metrics

        self.runtime_metrics.register_rl(rl_metrics.snapshot)
        # weight-distribution plane (kubedl_weights_* + per-pod
        # kubedl_model_version): distributors/relays in the process feed
        # the module singleton; register unconditionally (renders
        # nothing until a version is distributed)
        from kubedl_tpu.weights.metrics import weights_metrics

        self.runtime_metrics.register_weights(weights_metrics.snapshot)
        # flight recorder (docs/observability.md): control-plane tracer
        # routing spans into per-job dirs under trace_root, plus the
        # goodput accountant that folds those dirs into
        # kubedl_goodput_ratio on each scrape
        import tempfile

        from kubedl_tpu.obs import GoodputReporter, Tracer

        self.trace_root = self.config.trace_dir or tempfile.mkdtemp(
            prefix="kubedl-trace-")
        self.tracer = Tracer(service="operator", export_root=self.trace_root)
        self.goodput = GoodputReporter(self.trace_root)
        self.runtime_metrics.register_goodput(self.goodput.snapshot)
        self.step_aggregator = None  # set with the executor below
        self.manager = Manager(self.store, runtime_metrics=self.runtime_metrics)
        self.recorder = EventRecorder(self.store)
        self.metrics_registry = MetricsRegistry()
        self.gang_registry = GangRegistry()
        self.gang_registry.register(TPUSliceAdmitter.with_pool(self.store, self.config.tpu_slices))
        self._gang = self.gang_registry.get(self.config.gang_scheduler_name)
        if isinstance(self._gang, TPUSliceAdmitter):
            # admission grants retro-record the gang's queue wait as spans
            self._gang.tracer = self.tracer
        if self.config.tpu_slices and isinstance(self._gang, TPUSliceAdmitter):
            # BASELINE.md "slice utilization" gauge: /metrics + /debug/vars.
            # demand_rev is the version token: a scrape with no admitter
            # transition since the last one reuses the cached family text
            # (docs/control_plane_scale.md)
            self.runtime_metrics.register_slice_pool(
                self._gang.utilization, version_fn=self._gang.demand_rev)
        self.capacity_scheduler = None
        if self.config.scheduler_policy and isinstance(self._gang, TPUSliceAdmitter):
            from kubedl_tpu.sched import CapacityConfig, CapacityScheduler

            self.config.enable_gang_scheduling = True
            self.capacity_scheduler = CapacityScheduler(
                self._gang,
                self.store,
                CapacityConfig(
                    policy=self.config.scheduler_policy,
                    tenant_weights=self.config.tenant_weights,
                    tenant_caps=self.config.tenant_caps,
                    enable_preemption=self.config.enable_preemption,
                    preemption_backoff=self.config.preemption_backoff,
                    enable_elastic=self.config.enable_elastic,
                    shrink_delay=self.config.elastic_shrink_delay,
                    grow_delay=self.config.elastic_grow_delay,
                ),
            )
            self.capacity_scheduler.tracer = self.tracer
            self.runtime_metrics.register_capacity(
                self.capacity_scheduler.snapshot,
                version_fn=self.capacity_scheduler.version)
            self.manager.add_loop(
                "capacity-scheduler",
                self.capacity_scheduler.tick,
                self.config.scheduler_interval,
            )
        self.executor: Optional[LocalPodExecutor] = None
        if self.config.run_executor:
            scheduler = self._gang if self.config.tpu_slices else None
            self.executor = LocalPodExecutor(
                self.store, scheduler=scheduler, trace_root=self.trace_root)
            # per-step telemetry: pods heartbeat into their control dirs;
            # the aggregator scans them on each metrics scrape (straggler
            # detection + kubedl_step_time_seconds)
            from kubedl_tpu.obs import StepAggregator

            self.step_aggregator = StepAggregator(
                scan_fn=self.executor.read_heartbeats)
            self.runtime_metrics.register_steps(self.step_aggregator.snapshot)
        if self.capacity_scheduler is not None and self.executor is not None:
            # live-reshard control channel: the scheduler posts RESIZE
            # messages into running pods through the executor (kube mode
            # has no channel yet — resizes take the checkpoint path there)
            self.capacity_scheduler.attach_control(self.executor.post_control)
        self.reconcilers: Dict[str, JobReconciler] = {}
        self._kind_by_lower: Dict[str, str] = {}
        self._started = False
        self._stopping = threading.Event()
        self.elector = None  # FileLeaseElector | KubeLeaseElector
        self.node_inventory = None  # kube mode: slice pool from node labels
        self._podgroup_watch = None  # kube mode + gang: cache-only informer
        # storage persistence (ref main.go:97-100): backends resolved at
        # start() so every registered workload gets a persist controller
        self.object_backend = None
        self.event_backend = None
        self._persist_controllers: List = []
        # durable control plane (docs/ha.md): wired at start() so the
        # journal carries the fencing epoch of the WON election
        self.journal = None  # GrantJournal when config.journal_dir set
        self.history_store = None  # HistoryStore when config.history_dir set
        self._history_controllers: List = []
        # family registered even with the journal disabled so
        # kubedl_journal_* render as zeros and /debug/vars stays complete;
        # the snapshot doubles as its own version token (pure counters,
        # O(1)) so an unchanged scrape skips the re-format
        self.runtime_metrics.register_journal(
            self._journal_snapshot,
            version_fn=lambda: tuple(sorted(self._journal_snapshot().items())))

    # -- registration ----------------------------------------------------

    def register(self, controller) -> JobReconciler:
        """Register one workload controller (ref controllers/controllers.go:31-47)."""
        from kubedl_tpu.codesync import CodeSyncer

        mutators = []
        if self.kube_mode:
            from kubedl_tpu.k8s.gke import gke_tpu_mutator

            mutators.append(gke_tpu_mutator)
        engine = JobReconciler(
            self.store,
            controller,
            recorder=self.recorder,
            metrics=self.metrics_registry.for_kind(controller.kind),
            gang_scheduler=self._gang,
            code_syncer=CodeSyncer(),
            config=EngineConfig(
                enable_gang_scheduling=self.config.enable_gang_scheduling,
                cluster_domain=self.config.cluster_domain,
                pod_mutators=mutators,
            ),
        )
        controller.engine = engine
        engine.tracer = self.tracer  # reconcile spans on the job timeline
        runner = self.manager.add_controller(
            controller.controller_name, engine.reconcile, workers=self.config.max_reconciles
        )
        engine.setup(runner)
        self.reconcilers[controller.kind] = engine
        self._kind_by_lower[controller.kind.lower()] = controller.kind
        log.info("controller started kind=%s workers=%d",
                 controller.kind, self.config.max_reconciles)
        return engine

    @property
    def kube_mode(self) -> bool:
        from kubedl_tpu.k8s.store import KubeObjectStore

        return isinstance(self.store, KubeObjectStore)

    def register_all(self) -> None:
        from kubedl_tpu.controllers.registry import enabled_controllers

        # In kube mode the "auto" gate probes the discovery API for each
        # CRD, like the reference (ref workload_gate.go:26-107). Discovery
        # errors propagate (StoreError): better to crash-loop at startup
        # than come up silently reconciling nothing.
        discover = self.store.has_kind if self.kube_mode else None
        controllers = enabled_controllers(self.config.workloads, discover=discover)
        if discover is not None and not controllers:
            log.warning(
                "workload gate %r enabled no controllers (no matching CRDs "
                "served by the API server)", self.config.workloads,
            )
        for controller in controllers:
            self.register(controller)

    # -- lifecycle -------------------------------------------------------

    def start(self, timeout: Optional[float] = None) -> bool:
        """Start reconciling. With leader election enabled this blocks as a
        standby until the lease is won (ref main.go:70-75 semantics) or
        `timeout`/`stop()` interrupts it; returns False if never elected."""
        if self._started:
            return True
        if self.config.enable_leader_election:
            if self.kube_mode:
                # apiserver-backed Lease: replicas on different nodes
                # contend through coordination.k8s.io like the reference
                # (ref main.go:56,70-75); losing the lease stops the
                # manager — the reference's process would exit
                from kubedl_tpu.k8s.leader import KubeLeaseElector

                self.elector = KubeLeaseElector(
                    self.store.client,
                    namespace=self.config.kube_namespace,
                    lease_duration=self.config.leader_lease_duration,
                    renew_period=self.config.leader_renew_period,
                    retry_period=self.config.leader_retry_period,
                    on_lost=self._on_leadership_lost,
                )
            else:
                self.elector = FileLeaseElector(self.config.leader_lease_path)
            if not self.elector.acquire(timeout=timeout, stop=self._stopping.is_set):
                return False
        if self.config.journal_dir and isinstance(self._gang, TPUSliceAdmitter):
            # replay BEFORE the executor/manager start: pre-crash grants
            # must be restored (or conservatively parked as drains)
            # before anything can admit over them
            self._setup_journal()
        self._started = True
        self._setup_persistence()
        if self.executor is not None:
            self.executor.start()
        self.manager.start()
        if self.kube_mode and self.reconcilers:
            # informer cache: after sync, reconcile get/list never hits
            # the apiserver (ref reads from the informer cache, SURVEY
            # §3.2). Pod/Service pumps only exist when a controller
            # registered, so with zero controllers there is nothing to
            # wait for.
            kinds = sorted({*self.reconcilers, "Pod", "Service"})
            if self.config.enable_gang_scheduling and self.store.has_kind("PodGroup"):
                # the gang admitter mirrors PodGroups every reconcile; a
                # cache-only watch keeps those reads off the apiserver.
                # Guarded by discovery: without the CRD the pump would
                # relist a 404 forever and sync would stall startup
                # (mirror writes already tolerate the missing kind).
                self._podgroup_watch = self.store.watch(
                    ["PodGroup"], cache_only=True)
                kinds.append("PodGroup")
            if not self.store.wait_for_cache_sync(kinds, timeout=30.0):
                log.warning("informer cache not synced within 30s; reads stay uncached")
        if (
            self.kube_mode
            and not self.config.tpu_slices
            and isinstance(self._gang, TPUSliceAdmitter)
        ):
            # derive the slice pool from what GKE actually provisioned
            # (node labels), keeping --tpu-slices as an explicit override
            from kubedl_tpu.k8s.nodes import NodeInventory

            self.node_inventory = NodeInventory(
                self.store.client, on_change=self._gang.set_pool
            )
            self.node_inventory.start()
            self.runtime_metrics.register_slice_pool(
                self._gang.utilization, version_fn=self._gang.demand_rev)
        return True

    def _setup_journal(self) -> None:
        """Write-ahead grant/drain journal (docs/ha.md): open + replay
        against the observed pod set, stamped with the fencing epoch of
        the election we just won so a deposed predecessor's appends are
        refused loudly."""
        from kubedl_tpu.journal import GrantJournal

        epoch, authority = 0, None
        if isinstance(self.elector, FileLeaseElector):
            epoch = self.elector.epoch
            lease = self.elector.lease_path
            authority = lambda: read_epoch(lease)  # noqa: E731
        self.journal = GrantJournal(
            os.path.join(self.config.journal_dir, "grant.journal"),
            epoch=epoch,
            epoch_authority=authority,
            compact_bytes=self.config.journal_compact_bytes,
        )
        stats = self._gang.restore_from_journal(self.journal)
        if stats["records"]:
            log.info(
                "grant journal replayed: records=%d conflicts=%d gangs=%d",
                stats["records"], stats["conflicts"], stats["gangs"])

    def _journal_snapshot(self) -> Dict:
        """kubedl_journal_* + kubedl_leader_epoch source (metrics)."""
        snap = dict(self.journal.snapshot()) if self.journal is not None else {}
        snap["leader_epoch"] = (
            getattr(self.elector, "epoch", 0) or snap.get("epoch", 0))
        return snap

    def _setup_persistence(self) -> None:
        workload_controllers = {
            kind: engine.controller for kind, engine in self.reconcilers.items()
        }
        if self.config.object_storage or self.config.event_storage:
            from kubedl_tpu.controllers.persist import setup_persist_controllers
            from kubedl_tpu.storage import registry as storage_registry

            if self.config.object_storage:
                self.object_backend = storage_registry.new_object_backend(
                    self.config.object_storage, db_path=self.config.storage_db_path
                )
                self.object_backend.initialize()
            if self.config.event_storage:
                # share the object backend when both flags name the same backend
                # and it implements the event role too (sqlite does)
                if (
                    self.config.event_storage == self.config.object_storage
                    and hasattr(self.object_backend, "save_event")
                ):
                    self.event_backend = self.object_backend
                else:
                    self.event_backend = storage_registry.new_event_backend(
                        self.config.event_storage, db_path=self.config.storage_db_path
                    )
                    self.event_backend.initialize()
            self._persist_controllers = setup_persist_controllers(
                self.manager,
                self.store,
                workload_controllers,
                object_backend=self.object_backend,
                event_backend=self.event_backend,
                region=self.config.region,
            )
        if self.config.history_dir:
            # fleet history: joins its own JSONL evidence with whatever
            # job/event rows the backends above persist (both optional)
            from kubedl_tpu.journal import HistoryStore
            from kubedl_tpu.journal.history import setup_history_controllers

            self.history_store = HistoryStore(
                self.config.history_dir,
                object_backend=self.object_backend,
                event_backend=self.event_backend,
                region=self.config.region,
                retention_max_age_s=self.config.history_retention_max_age_s,
                retention_max_bytes=self.config.history_retention_max_bytes,
            )
            self.history_store.initialize()
            self._history_controllers = setup_history_controllers(
                self.manager,
                self.store,
                workload_controllers,
                self.history_store,
                self.trace_root,
            )

    def _on_leadership_lost(self) -> None:
        log.error("leadership lost — stopping reconcilers (standby takes over)")
        self.stop()

    def stop(self) -> None:
        self._stopping.set()
        if self._podgroup_watch is not None:
            self._podgroup_watch.stop()
        if self.node_inventory is not None:
            self.node_inventory.stop()
        self.manager.stop()
        if self.elector is not None:
            self.elector.release()
        if self.executor is not None:
            self.executor.stop()
        if self.journal is not None:
            self.journal.close()
        if self.history_store is not None:
            self.history_store.close()
        self.tracer.close()
        if self.object_backend is not None:
            self.object_backend.close()
        if self.event_backend is not None and self.event_backend is not self.object_backend:
            self.event_backend.close()
        if self._owns_store:
            # ObjectStore.close() stops the GC sweeper; KubeObjectStore
            # exposes stop() for its informer/watch threads
            stopper = getattr(self.store, "close", None) or getattr(
                self.store, "stop", None)
            if stopper is not None:
                stopper()

    # -- client-ish helpers ---------------------------------------------

    def report_slice_failure(self, slice_name: str) -> None:
        """A pool slice died mid-run (hardware fault / maintenance). With
        a capacity scheduler, the owning gang is offered a live shrink to
        a declared fallback shape (fault tolerance as cheap shrink,
        docs/scheduling.md); otherwise the dead slice drains out of the
        pool and the gang's pods take the checkpoint-evict path."""
        if self.capacity_scheduler is not None:
            self.capacity_scheduler.slice_failed(slice_name)
            return
        if isinstance(self._gang, TPUSliceAdmitter):
            gang_key = self._gang.slice_failed(slice_name)
            if gang_key is None:
                return
            # no scheduler to orchestrate a live shrink: checkpoint-evict
            # (shared kind-guarded pod selection — gang/interface.py)
            from kubedl_tpu.gang.interface import gang_pods

            namespace, _, name = gang_key.partition("/")
            state = self._gang.get_gang(namespace, name)
            kind = getattr(state, "kind", "") if state is not None else ""
            for pod in gang_pods(self.store, gang_key, kind):
                try:
                    self.store.delete(
                        "Pod", pod.metadata.namespace, pod.metadata.name)
                except NotFound:
                    pass

    def apply(self, manifest: Dict):
        """kubectl-apply equivalent: route a manifest dict to its typed job."""
        kind = manifest.get("kind", "")
        canonical = self._kind_by_lower.get(kind.lower())
        if canonical is None:
            raise ValueError(
                f"no controller registered for kind {kind!r} "
                f"(enabled: {sorted(self.reconcilers)})"
            )
        engine = self.reconcilers[canonical]
        job_cls = engine.controller.job_type()
        job = from_dict(job_cls, manifest)
        job.kind = canonical
        # admission: default then validate (the webhook pair the reference
        # scaffolds but never implements — api/validation.py)
        engine.controller.set_defaults(job)
        validate(job, engine.controller)
        try:
            existing = self.store.get(canonical, job.metadata.namespace, job.metadata.name)
            job.metadata.resource_version = existing.metadata.resource_version
            job.metadata.uid = existing.metadata.uid
            job.status = existing.status
            return self.store.update(job)
        except NotFound:
            return self.store.create(job)

    def get_job(self, kind: str, namespace: str, name: str):
        return self.store.get(self._kind_by_lower.get(kind.lower(), kind), namespace, name)

    def wait_for_condition(
        self, job, condition: str, timeout: float = 30.0, poll: float = 0.02
    ) -> bool:
        import time

        ctype = JobConditionType(condition)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                fresh = self.store.get(job.kind, job.metadata.namespace, job.metadata.name)
            except NotFound:
                time.sleep(poll)
                continue
            if has_condition(fresh.status, ctype):
                return True
            time.sleep(poll)
        return False
