"""The repo-specific invariant passes (docs/static_analysis.md).

Each pass encodes one invariant class CHANGES.md shows drifting by hand
across review rounds — the pass is the reviewer's checklist item turned
into a machine check. Scopes are deliberate and documented per pass:
tests/ is excluded where tests legitimately violate the invariant (e.g.
hand-building expected Prometheus lines, writing corrupt npz fixtures).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from kubedl_tpu.analysis.framework import (
    AnalysisPass,
    Finding,
    RepoContext,
    SourceFile,
)

# callables that render an ALREADY-ESCAPED label value; interpolating
# one of these into a label position is the blessed discipline
_ESCAPERS = {"escape_label_value", "_label"}
# the one module allowed to state the escaping rules
_PROM_HELPER = "kubedl_tpu/metrics/prom.py"


def _in_tests(path: str) -> bool:
    return path.startswith("tests/")


# ---------------------------------------------------------------------------
# prom-escape
# ---------------------------------------------------------------------------


class PromEscapePass(AnalysisPass):
    """A ``kubedl_*`` exposition line rendered by hand must escape every
    interpolated label VALUE through metrics/prom.py helpers — one stray
    quote in a tenant/job/slice name blanks the whole scrape (the PR 10
    lesson). %-format and .format() renders of label lines are flagged
    outright: they cannot carry the escaping call at the value site."""

    id = "prom-escape"
    description = ("kubedl_* metric lines with unescaped interpolated "
                   "label values outside metrics/prom.py")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.path == _PROM_HELPER or _in_tests(src.path):
                # tests hand-build EXPECTED exposition lines; the helper
                # module IS the escaping discipline
                continue
            # inner BinOps of an already-flagged concatenation chain
            # (a + b + c parses as nested Adds) must not double-report
            flagged_concat: set = set()
            for node in ast.walk(src.tree):
                if isinstance(node, ast.JoinedStr):
                    out.extend(self._check_fstring(src, node))
                elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
                    lit = self._label_literal(node.left)
                    if lit is not None:
                        out.append(Finding(
                            self.id, src.path, node.lineno,
                            "%-format renders a kubedl_* label line — use "
                            "an f-string with escape_label_value() or "
                            "prom.sample()"))
                elif (isinstance(node, ast.BinOp)
                      and isinstance(node.op, ast.Add)
                      and id(node) not in flagged_concat
                      and self._concat_renders_labels(node)):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.BinOp):
                            flagged_concat.add(id(sub))
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        "string concatenation renders a kubedl_* label "
                        "line — use an f-string with escape_label_value() "
                        "or prom.sample()"))
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "format"):
                    lit = self._label_literal(node.func.value)
                    if lit is not None:
                        out.append(Finding(
                            self.id, src.path, node.lineno,
                            ".format() renders a kubedl_* label line — use "
                            "an f-string with escape_label_value() or "
                            "prom.sample()"))
        return out

    @staticmethod
    def _label_literal(node: ast.AST) -> Optional[str]:
        """The string constant when `node` is a kubedl_* exposition
        template with a label block, else None."""
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and "kubedl_" in node.value and '="' in node.value):
            return node.value
        return None

    @classmethod
    def _concat_renders_labels(cls, node: ast.BinOp) -> bool:
        """True when an Add-chain splices dynamic values into a
        kubedl_* label template (``'kubedl_x{job="' + job + '"} 1'``) —
        the escape call cannot be checked at the value site, so the
        whole construction is flagged like %-format."""
        has_template = has_dynamic = False
        for sub in ast.walk(node):
            if cls._label_literal(sub) is not None:
                has_template = True
            elif isinstance(sub, (ast.Name, ast.Call, ast.Attribute,
                                  ast.Subscript, ast.JoinedStr)):
                has_dynamic = True
        return has_template and has_dynamic

    def _check_fstring(self, src: SourceFile, node: ast.JoinedStr) -> List[Finding]:
        # Only f-strings that render a metric line WITH labels matter:
        # some literal segment mentions kubedl_ and some segment opens a
        # label value (ends with `="`). Values interpolated right after
        # a `="` must be escape calls.
        literals = [
            v.value for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        if not any("kubedl_" in s for s in literals):
            return []
        if not any(s.rstrip().endswith('="') or '="' in s for s in literals):
            return []
        out: List[Finding] = []
        in_label_value = False
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                # walking the literal text tracks whether the NEXT
                # interpolation lands between label-value quotes
                for ch_idx in range(len(v.value)):
                    if v.value[ch_idx] == '"':
                        in_label_value = v.value[:ch_idx].endswith("=")
                continue
            if isinstance(v, ast.FormattedValue) and in_label_value:
                if not self._is_escaped(v.value):
                    out.append(Finding(
                        self.id, src.path, v.value.lineno,
                        f"label value interpolates "
                        f"{{{src.segment(v.value) or '?'}}} unescaped — "
                        f"wrap it in escape_label_value()/_label() or "
                        f"render through prom.sample()"))
        return out

    @staticmethod
    def _is_escaped(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in _ESCAPERS


# ---------------------------------------------------------------------------
# debug-vars-family
# ---------------------------------------------------------------------------

_RUNTIME_METRICS = "kubedl_tpu/metrics/runtime_metrics.py"
_METRICS_DOC = "docs/metrics.md"
_METRIC_NAME_RE = re.compile(r"kubedl_[a-z0-9_]+")


def runtime_metric_families(src_text: Optional[str] = None,
                            root: str = "") -> List[str]:
    """The ``register_*`` family names on RuntimeMetrics, derived from
    the AST — the machine-maintained half of what
    test_debug_vars_has_every_newer_family used to hand-list."""
    if src_text is None:
        import os

        with open(os.path.join(root or ".", _RUNTIME_METRICS)) as f:
            src_text = f.read()
    tree = ast.parse(src_text)
    fams: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "RuntimeMetrics":
            for item in node.body:
                if (isinstance(item, ast.FunctionDef)
                        and item.name.startswith("register_")):
                    fams.append(item.name[len("register_"):])
    return fams


class DebugVarsFamilyPass(AnalysisPass):
    """Every ``register_<family>`` snapshot hook on RuntimeMetrics must
    be (a) read back in ``debug_vars()`` (or `kubedl-tpu top` can never
    show it), (b) rendered in ``render()`` (or /metrics silently lacks
    the family), and (c) every metric name that family renders must
    appear in docs/metrics.md."""

    id = "debug-vars-family"
    description = ("RuntimeMetrics register_* families missing from "
                   "/debug/vars, /metrics, or docs/metrics.md")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        src = next((s for s in files if s.path == _RUNTIME_METRICS), None)
        if src is None:
            return []
        cls = next(
            (n for n in ast.walk(src.tree)
             if isinstance(n, ast.ClassDef) and n.name == "RuntimeMetrics"),
            None)
        if cls is None:
            return [Finding(self.id, src.path, 1,
                            "class RuntimeMetrics not found")]
        registers: Dict[str, ast.FunctionDef] = {}
        methods: Dict[str, ast.FunctionDef] = {}
        for item in cls.body:
            if isinstance(item, ast.FunctionDef):
                methods[item.name] = item
                if item.name.startswith("register_"):
                    registers[item.name[len("register_"):]] = item
        out: List[Finding] = []
        dv = methods.get("debug_vars")
        render = methods.get("render")
        doc = ctx.doc_text(_METRICS_DOC)
        for family, reg in sorted(registers.items()):
            attrs = self._stored_attrs(reg)
            if not attrs:
                out.append(Finding(
                    self.id, src.path, reg.lineno,
                    f"register_{family} stores no self attribute — the "
                    f"family cannot be rendered"))
                continue
            for method, surface in ((dv, "/debug/vars (debug_vars)"),
                                    (render, "/metrics (render)")):
                if method is None or not (attrs & self._read_attrs(method)):
                    out.append(Finding(
                        self.id, src.path, reg.lineno,
                        f"register_{family} family is missing from "
                        f"{surface} — a registered snapshot must be on "
                        f"both surfaces"))
            if render is not None:
                for name in self._rendered_metric_names(src, render, attrs):
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    if base not in doc and name not in doc:
                        out.append(Finding(
                            self.id, src.path, reg.lineno,
                            f"metric {name} (family {family}) is not "
                            f"documented in {_METRICS_DOC}"))
        return out

    @staticmethod
    def _stored_attrs(fn: ast.FunctionDef) -> Set[str]:
        """self attributes a register_* method assigns (plain or
        subscripted: ``self._x = fn`` / ``self._x[k] = fn``)."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        t = t.value
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
        return out

    @staticmethod
    def _read_attrs(fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and isinstance(node.ctx, ast.Load)):
                out.add(node.attr)
        return out

    @staticmethod
    def _rendered_metric_names(src: SourceFile, render: ast.FunctionDef,
                               attrs: Set[str]) -> Set[str]:
        """kubedl_* names rendered by the family's guarded block in
        render(): find ``<var> = self.<attr>`` then the ``if <var> …``
        statement using it, and regex the block's source. Families
        rendered inline (no var-guard, e.g. the histogram core) fall
        back to names near the attr's own statements — best-effort, the
        docs check is advisory coverage, not a proof."""
        names: Set[str] = set()
        guard_vars: Set[str] = set()
        for node in ast.walk(render):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, v = node.targets[0], node.value
                if (isinstance(t, ast.Name) and isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id == "self" and v.attr in attrs):
                    guard_vars.add(t.id)
        for node in ast.walk(render):
            if isinstance(node, ast.If):
                test_names = {
                    n.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Name)}
                if test_names & guard_vars:
                    names.update(
                        _METRIC_NAME_RE.findall(src.segment(node)))
        return names


# ---------------------------------------------------------------------------
# shared-validation
# ---------------------------------------------------------------------------


class SharedValidationPass(AnalysisPass):
    """Workload modules must not fork shape/validation rules away from
    api/validation — submit-time and runtime checks drift apart exactly
    when a workload grows a local ``validate_*`` (the PR 9/13 lesson:
    validate_pipeline_shapes / validate_rl_shapes live in ONE place and
    both sides call them). The controller hook ``validate_job`` is the
    blessed entry point; everything else belongs in api/validation."""

    id = "shared-validation"
    description = ("local validate_* definitions in workload modules "
                   "bypassing api/validation")

    _ALLOWED = {"validate_job"}

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if not src.path.startswith("kubedl_tpu/workloads/"):
                continue
            for node in ast.walk(src.tree):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and re.match(r"^_?validate_", node.name)
                        and node.name not in self._ALLOWED):
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        f"{node.name} defines validation rules locally — "
                        f"move the rule into api/validation so submit and "
                        f"runtime enforce one rule set"))
        return out


# ---------------------------------------------------------------------------
# payload-dtype
# ---------------------------------------------------------------------------

# modules allowed to state an array-serialization format: each records
# dtypes explicitly and round-trips bf16 as raw uint8 (the npz |V2
# lesson from PR 6/8/9)
_CODEC_MODULES = {
    "kubedl_tpu/serving/handoff.py",     # serialized KV (rows_dtype)
    "kubedl_tpu/train/reshard_runtime.py",  # staged shard blocks
    "kubedl_tpu/rl/wire.py",             # named-array record codec
}
_NUMPY_SAVERS = {"save", "savez", "savez_compressed"}


class PayloadDtypePass(AnalysisPass):
    """Array payloads may be serialized only by the blessed codec
    modules (wire/boundary/handoff): everything else must route through
    them, because raw-uint8 + recorded dtype is the only discipline that
    survives bf16 (np.savez alone void-types it to |V2, pickle pins the
    producer's class layout)."""

    id = "payload-dtype"
    description = ("np.save/np.savez/pickle outside the blessed codec "
                   "modules")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.path in _CODEC_MODULES or _in_tests(src.path):
                # tests build corrupt/raw fixtures on purpose
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not isinstance(fn, ast.Attribute):
                    continue
                base = fn.value
                base_name = base.id if isinstance(base, ast.Name) else ""
                if (base_name in ("np", "numpy")
                        and fn.attr in _NUMPY_SAVERS):
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        f"{base_name}.{fn.attr} serializes arrays outside "
                        f"the blessed codecs — bf16 dies in npz (|V2); "
                        f"route through serving/handoff, rl/wire, or the "
                        f"reshard staging codec"))
                elif base_name == "pickle" and fn.attr in (
                        "dump", "dumps", "load", "loads"):
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        f"pickle.{fn.attr} on payloads is forbidden — it "
                        f"pins class layout and is unsafe across "
                        f"incarnations; use an explicit dtype-recorded "
                        f"codec"))
        return out


# ---------------------------------------------------------------------------
# broad-except
# ---------------------------------------------------------------------------

_NOQA_BLE = re.compile(r"#\s*noqa:\s*BLE001\b\s*(?:[—–-]+\s*(?P<why>\S.*))?")
_LOUD_ATTRS = {
    # logging-ish routing: the failure is visible downstream
    "exception", "error", "warning", "critical", "info", "debug",
}


class BroadExceptPass(AnalysisPass):
    """``except Exception`` may not swallow silently: the handler must
    re-raise, route the failure loudly (logger / recorder / print /
    classified EXIT_* code from utils/exit_codes), or carry a justified
    pragma. The repo's ``# noqa: BLE001 — why`` idiom on the except
    line counts as the pragma; a BARE ``noqa: BLE001`` on a swallowing
    handler is flagged — the why must travel with the suppression."""

    id = "broad-except"
    description = ("except Exception handlers that swallow without "
                   "re-raise, loud routing, or a justified pragma")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not self._is_broad(node.type):
                    continue
                if self._handler_is_loud(node):
                    continue
                line_text = (src.lines[node.lineno - 1]
                             if node.lineno - 1 < len(src.lines) else "")
                m = _NOQA_BLE.search(line_text)
                if m and m.group("why"):
                    continue  # the justified-noqa idiom IS the pragma
                if m:
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        "broad except swallows behind a BARE noqa: BLE001 "
                        "— add the justification (`# noqa: BLE001 — why`)"))
                else:
                    out.append(Finding(
                        self.id, src.path, node.lineno,
                        "broad except swallows silently — re-raise, route "
                        "through a logger/recorder or the exit taxonomy, "
                        "or justify with `# noqa: BLE001 — why`"))
        return out

    @staticmethod
    def _is_broad(type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare except
        names = []
        for n in ([type_node] if not isinstance(type_node, ast.Tuple)
                  else list(type_node.elts)):
            if isinstance(n, ast.Name):
                names.append(n.id)
        return any(n in ("Exception", "BaseException") for n in names)

    @classmethod
    def _handler_is_loud(cls, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Return):
                # returning a classified exit code routes the failure
                # through the retryable/permanent taxonomy
                v = node.value
                if isinstance(v, ast.Name) and v.id.startswith("EXIT_"):
                    return True
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr in _LOUD_ATTRS:
                        return True
                    if fn.attr in ("exit", "_exit"):
                        return True  # sys.exit / os._exit with a code
                elif isinstance(fn, ast.Name) and fn.id == "print":
                    # pod programs log via print; a printed failure is
                    # not a silent one
                    return True
        return False


# ---------------------------------------------------------------------------
# bench-lane-merge
# ---------------------------------------------------------------------------

_EXTRAS_FILE = ".bench_extras.json"
# functions allowed to touch .bench_extras.json directly: the shared
# guarded-merge lane body and the full-run snapshot merge in main()
_EXTRAS_BLESSED = {"_single_lane", "main"}


class BenchLaneMergePass(AnalysisPass):
    """Bench lanes must fold ONLY their own keys into .bench_extras.json
    and only through ``_single_lane`` — a CPU smoke lane that clobbers
    the chip's committed peak/probe records destroys acceptance
    evidence (the PR 6 lesson, restated for every later lane)."""

    id = "bench-lane-merge"
    description = (".bench_extras.json touched outside _single_lane, or "
                   "a lane merging keys it does not produce")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.path != "bench.py":
                continue
            func_of: Dict[int, str] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if hasattr(sub, "lineno"):
                            func_of.setdefault(sub.lineno, node.name)
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.Constant)
                        and node.value == _EXTRAS_FILE):
                    fn = func_of.get(node.lineno, "<module>")
                    if fn not in _EXTRAS_BLESSED:
                        out.append(Finding(
                            self.id, src.path, node.lineno,
                            f"{_EXTRAS_FILE} referenced in {fn}() — lanes "
                            f"merge through _single_lane(merge_keys=...) "
                            f"only"))
                if isinstance(node, ast.Call):
                    fn_name = (node.func.id
                               if isinstance(node.func, ast.Name) else "")
                    if fn_name != "_single_lane":
                        continue
                    milestones = self._str_tuple(
                        node.args[1] if len(node.args) > 1 else None)
                    merge_keys = None
                    for kw in node.keywords:
                        if kw.arg == "merge_keys":
                            merge_keys = self._str_tuple(kw.value)
                    if milestones is None or not merge_keys:
                        continue
                    extra = set(merge_keys) - set(milestones)
                    if extra:
                        out.append(Finding(
                            self.id, src.path, node.lineno,
                            f"lane merges keys it does not produce: "
                            f"{sorted(extra)} not among milestones "
                            f"{sorted(milestones)} — another lane's "
                            f"committed record would be clobbered"))
        return out

    @staticmethod
    def _str_tuple(node: Optional[ast.AST]) -> Optional[List[str]]:
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = []
            for e in node.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)):
                    return None
                vals.append(e.value)
            return vals
        return None
