"""``python -m kubedl_tpu.analysis`` — the `make lint` / presubmit gate.

Exit code 0 when the tree has zero unallowlisted findings, 1 otherwise
(2 on usage errors). ``kubedl-tpu analyze`` is the same runner behind
the operator CLI so the report is inspectable the way `top`/`trace`
are.
"""
from __future__ import annotations

import argparse
import os
import sys

from kubedl_tpu.analysis.framework import run_analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubedl_tpu.analysis", description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detect from this file)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--no-tests", action="store_true",
                    help="skip tests/ (the default scope includes it)")
    ap.add_argument("--show-allowlisted", action="store_true",
                    help="also print pragma-suppressed findings")
    args = ap.parse_args(argv)
    root = args.root
    if root is None:
        # kubedl_tpu/analysis/__main__.py -> repo root two levels up
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(root, "kubedl_tpu")):
        print(f"error: {root} does not look like the repo root "
              f"(no kubedl_tpu/)", file=sys.stderr)
        return 2
    report = run_analysis(root, include_tests=not args.no_tests)
    if args.json:
        print(report.to_json())
    else:
        print(report.to_text())
        if args.show_allowlisted and report.allowlisted:
            print("-- allowlisted --")
            for f in report.allowlisted:
                print(f.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
