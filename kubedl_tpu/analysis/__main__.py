"""``python -m kubedl_tpu.analysis`` — the `make lint` / presubmit gate.

Exit code 0 when the tree has zero unallowlisted findings, 1 otherwise
(2 on usage errors). ``kubedl-tpu analyze`` is the same runner behind
the operator CLI so the report is inspectable the way `top`/`trace`
are.
"""
from __future__ import annotations

import argparse
import os
import sys

from kubedl_tpu.analysis.framework import default_passes, run_analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubedl_tpu.analysis", description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detect from this file)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--no-tests", action="store_true",
                    help="skip tests/ (the default scope includes it)")
    ap.add_argument("--show-allowlisted", action="store_true",
                    help="also print pragma-suppressed findings")
    ap.add_argument("--only", default="",
                    help="comma-separated pass ids to run (see "
                         "--list-passes); unknown ids are a usage error")
    ap.add_argument("--list-passes", action="store_true",
                    help="print the registered pass ids and exit")
    ap.add_argument("--model", action="store_true",
                    help="also run the protocol model checker "
                         "(kubedl_tpu.analysis.model) — exhaustive "
                         "admitter/scheduler state exploration")
    args = ap.parse_args(argv)
    passes = default_passes()
    if args.list_passes:
        for p in passes:
            print(f"{p.id}: {p.description}")
        return 0
    if args.only:
        wanted = [t.strip() for t in args.only.split(",") if t.strip()]
        known = {p.id for p in passes}
        bad = [t for t in wanted if t not in known]
        if bad:
            print(f"error: unknown pass id(s): {', '.join(bad)} "
                  f"(see --list-passes)", file=sys.stderr)
            return 2
        passes = [p for p in passes if p.id in wanted]
    root = args.root
    if root is None:
        # kubedl_tpu/analysis/__main__.py -> repo root two levels up
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(root, "kubedl_tpu")):
        print(f"error: {root} does not look like the repo root "
              f"(no kubedl_tpu/)", file=sys.stderr)
        return 2
    model_rc = 0
    if args.model:
        from kubedl_tpu.analysis.model import model_report
        model_rc = model_report()
    report = run_analysis(root, passes=passes,
                          include_tests=not args.no_tests)
    if args.json:
        print(report.to_json())
    else:
        print(report.to_text())
        if args.show_allowlisted and report.allowlisted:
            print("-- allowlisted --")
            for f in report.allowlisted:
                print(f.render())
    return model_rc or (0 if report.ok else 1)


if __name__ == "__main__":
    sys.exit(main())
