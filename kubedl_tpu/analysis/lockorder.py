"""Static lock-acquisition-order analysis over the concurrent planes.

The operator/admitter/transport planes hold locks across five concurrent
subsystems (admitter grants call director hooks, the scheduler drives
the admitter, the transport plane's peers serialize sends, the serving
router fans out to pod locks) and nothing checked acquisition order —
the Go reference leans on ``go vet``/``-race``; this is the Python
port's equivalent, the way PAPERS.md's Runtime Concurrency Control work
argues ordering discipline must be checked by the system.

What it does, per the target modules (transport/ gang/ sched/ serving/
core/ by default):

  1. index every lock: ``self.x = threading.Lock()/RLock()/Condition()``
     and the witness wrappers ``new_lock()/new_rlock()``; a
     ``Condition(self.other)`` aliases the lock it wraps;
  2. walk every function tracking the held-lock stack through
     ``with self.x:`` regions, resolving calls made under a held lock —
     ``self.m()``, ``self.attr.m()`` via __init__ assignment/annotation,
     module functions, module-level singletons, plus the explicit
     bindings below for couplings the AST cannot see (the admitter's
     director IS the capacity scheduler);
  3. fixpoint the transitive effects (locks acquired, I/O performed) of
     every function, then emit:
       * ``lock-order`` — cycles in the acquired-while-holding graph
         (and non-reentrant self-acquisition), each a potential
         deadlock;
       * ``lock-io`` — blocking I/O (socket send/accept/dial,
         ``time.sleep``, file ``open``, ``post_control``, subprocess)
         reachable while a lock is held: a stalled peer or slow volume
         must never pin a plane-wide lock.

Honest limits (documented in docs/static_analysis.md): calls through
bare ``Callable`` values (the metrics snapshot callbacks, workqueue
handlers) are invisible — the discipline there is "copy under the lock,
call outside it", which the passes CAN see when violated via direct
attribute calls. A pragma on the ``with`` line (or the flagged call
line) suppresses a finding with a justification:

    with self.lock:  # kubedl-analysis: allow[lock-io] one in-flight MSG per connection IS the serialization contract
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubedl_tpu.analysis.framework import (
    AnalysisPass,
    Finding,
    RepoContext,
    SourceFile,
)

DEFAULT_SCOPE = (
    "kubedl_tpu/transport/",
    "kubedl_tpu/gang/",
    "kubedl_tpu/sched/",
    "kubedl_tpu/serving/",
    "kubedl_tpu/core/",
)

# interface class -> concrete implementation wired at runtime
# (admitter.set_director(capacity_scheduler)); the AST alone sees only
# the abstract hooks
IMPLEMENTS = {
    "CapacityDirector": "CapacityScheduler",
    "GangScheduler": "TPUSliceAdmitter",
}

# (class, attr) -> concrete class, for couplings assigned from UNTYPED
# constructor params (the scheduler's `admitter` arg carries no
# annotation; the runtime wiring is operator.py's)
EXTRA_ATTR_BINDINGS = {
    ("CapacityScheduler", "admitter"): "TPUSliceAdmitter",
    ("CapacityScheduler", "store"): "ObjectStore",
}

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "new_lock": "lock",
               "new_rlock": "rlock"}


def _io_desc(call: ast.Call) -> Optional[str]:
    """Non-None when this call IS a blocking-I/O primitive."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "open()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    base = fn.value.id if isinstance(fn.value, ast.Name) else ""
    if attr in ("sendall", "accept", "makefile", "sendto"):
        return f".{attr}()"
    if attr == "connect" and ("sock" in base or "conn" in base):
        return ".connect()"
    if attr == "create_connection":
        return "socket.create_connection"
    if attr == "recv" and ("sock" in base or "conn" in base):
        return ".recv()"
    if attr == "sleep" and base == "time":
        return "time.sleep"
    if attr == "urlopen":
        return "urlopen"
    if attr in ("replace", "rename", "makedirs") and base == "os":
        return f"os.{attr}"
    if attr in ("run", "check_call", "check_output", "Popen") and (
            base == "subprocess"):
        return f"subprocess.{attr}"
    if attr == "post_control":
        return "post_control"
    return None


# a held lock: (lock key, line where THIS function acquired it) — the
# line anchors findings so ONE pragma on the `with` covers the region
Held = Tuple[str, int]


@dataclass
class _FuncInfo:
    qual: str  # "module.py:Class.method" or "module.py:func"
    module: str
    cls: Optional[str]
    node: ast.AST
    # (held locks at that point, acquired lock key, line)
    acquires: List[Tuple[Tuple[Held, ...], str, int]] = field(
        default_factory=list)
    # (held locks, call node, line) — every call, held or not
    calls: List[Tuple[Tuple[Held, ...], ast.Call, int]] = field(
        default_factory=list)
    # (held locks, line, desc) — direct I/O primitives
    io: List[Tuple[Tuple[Held, ...], int, str]] = field(default_factory=list)


@dataclass
class _ClassInfo:
    name: str
    module: str
    line: int
    locks: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    aliases: Dict[str, str] = field(default_factory=dict)  # cond -> lock attr
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class
    methods: Dict[str, _FuncInfo] = field(default_factory=dict)

    def lock_key(self, attr: str) -> str:
        attr = self.aliases.get(attr, attr)
        mod = (self.module.removeprefix("kubedl_tpu/")
               .removesuffix(".py").replace("/", "."))
        return f"{mod}.{self.name}.{attr}"


class LockOrderAnalyzer:
    def __init__(self, files: Sequence[SourceFile]) -> None:
        self.files = list(files)
        self.classes: Dict[str, List[_ClassInfo]] = {}  # name -> infos
        self.mod_funcs: Dict[Tuple[str, str], _FuncInfo] = {}
        self.singletons: Dict[Tuple[str, str], str] = {}  # (mod, name) -> cls
        # per-module imported names:
        # (mod, local name) -> (source module rel, original name)
        self.imports: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.lock_kind: Dict[str, str] = {}  # lock key -> lock|rlock
        self._effects: Dict[str, Tuple[Set[str], Set[str]]] = {}
        self._index()

    # -- indexing --------------------------------------------------------

    def _index(self) -> None:
        for src in self.files:
            for node in src.tree.body:
                if isinstance(node, ast.ImportFrom) and node.module:
                    rel = node.module.replace(".", "/") + ".py"
                    for alias in node.names:
                        # keyed by the LOCAL name, resolving back to the
                        # definition name (`import foo as bar` must find foo)
                        self.imports[(src.path, alias.asname or alias.name)] = (
                            rel, alias.name)
                if isinstance(node, ast.ClassDef):
                    info = self._index_class(src, node)
                    self.classes.setdefault(info.name, []).append(info)
                elif isinstance(node, ast.FunctionDef):
                    fi = _FuncInfo(
                        qual=f"{src.path}:{node.name}", module=src.path,
                        cls=None, node=node)
                    self._scan_func(fi, None, node)
                    self.mod_funcs[(src.path, node.name)] = fi
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t, v = node.targets[0], node.value
                    if (isinstance(t, ast.Name) and isinstance(v, ast.Call)
                            and isinstance(v.func, ast.Name)):
                        self.singletons[(src.path, t.id)] = v.func.id

    def _index_class(self, src: SourceFile, node: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(name=node.name, module=src.path, line=node.lineno)
        # first sweep: lock attrs + attr types from every method body
        for meth in node.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            ann: Dict[str, str] = {}
            for a in meth.args.args + meth.args.kwonlyargs:
                cls_name = _ann_class(a.annotation)
                if cls_name:
                    ann[a.arg] = cls_name
            for sub in ast.walk(meth):
                if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                    continue
                t, v = sub.targets[0], sub.value
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if isinstance(v, ast.Call):
                    ctor = v.func
                    ctor_name = (
                        ctor.id if isinstance(ctor, ast.Name)
                        else ctor.attr if isinstance(ctor, ast.Attribute)
                        else "")
                    if ctor_name in _LOCK_CTORS:
                        info.locks[t.attr] = _LOCK_CTORS[ctor_name]
                    elif ctor_name == "Condition":
                        if (v.args and isinstance(v.args[0], ast.Attribute)
                                and isinstance(v.args[0].value, ast.Name)
                                and v.args[0].value.id == "self"):
                            info.aliases[t.attr] = v.args[0].attr
                        else:
                            # bare Condition() wraps its own RLock
                            info.locks[t.attr] = "rlock"
                    elif ctor_name and ctor_name[0].isupper():
                        info.attr_types[t.attr] = ctor_name
                elif isinstance(v, ast.Name) and v.id in ann:
                    info.attr_types[t.attr] = ann[v.id]
        for attr, kind in info.locks.items():
            # register keys now so kind lookups work during scans
            self.lock_kind[info.lock_key(attr)] = kind
        for meth in node.body:
            if isinstance(meth, ast.FunctionDef):
                fi = _FuncInfo(
                    qual=f"{src.path}:{node.name}.{meth.name}",
                    module=src.path, cls=node.name, node=meth)
                self._scan_func(fi, info, meth)
                info.methods[meth.name] = fi
        return info

    # -- per-function scan (structured, held-stack aware) ----------------

    def _scan_func(self, fi: _FuncInfo, cls: Optional[_ClassInfo],
                   fn: ast.FunctionDef) -> None:
        self._scan_stmts(fi, cls, fn.body, held=())

    def _scan_stmts(self, fi: _FuncInfo, cls: Optional[_ClassInfo],
                    stmts: Sequence[ast.stmt],
                    held: Tuple[Held, ...]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # deferred execution — not part of this flow
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in st.items:
                    key = self._lock_expr_key(cls, item.context_expr)
                    if key is not None:
                        fi.acquires.append((new_held, key, st.lineno))
                        new_held = new_held + ((key, st.lineno),)
                    else:
                        self._scan_expr(fi, item.context_expr, held)
                self._scan_stmts(fi, cls, st.body, new_held)
                continue
            # every other statement: scan expressions at this held depth,
            # then recurse into compound bodies
            for expr in _stmt_exprs(st):
                self._scan_expr(fi, expr, held)
            for body in _stmt_bodies(st):
                self._scan_stmts(fi, cls, body, held)

    def _scan_expr(self, fi: _FuncInfo, expr: ast.AST,
                   held: Tuple[Held, ...]) -> None:
        # explicit traversal so DEFERRED bodies (lambdas, generator
        # expressions) are pruned — ast.walk would descend into them and
        # attribute their calls to the held region they merely close over
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.GeneratorExp)):
                continue
            if isinstance(node, ast.Call):
                desc = _io_desc(node)
                if desc is not None:
                    fi.io.append((held, node.lineno, desc))
                else:
                    fi.calls.append((held, node, node.lineno))
            stack.extend(ast.iter_child_nodes(node))

    def _lock_expr_key(self, cls: Optional[_ClassInfo],
                       expr: ast.AST) -> Optional[str]:
        """Lock key when `expr` is ``self.<lock-or-cond-attr>`` of the
        enclosing class (or ``self.<attr>.lock`` style is NOT handled —
        locks live on self by convention)."""
        if cls is None:
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            attr = cls.aliases.get(expr.attr, expr.attr)
            if attr in cls.locks:
                return cls.lock_key(attr)
        return None

    # -- call resolution -------------------------------------------------

    def _resolve_class(self, name: str) -> Optional[_ClassInfo]:
        name = IMPLEMENTS.get(name, name)
        infos = self.classes.get(name)
        if infos and len(infos) == 1:
            return infos[0]
        return None

    def _resolve_call(self, fi: _FuncInfo,
                      call: ast.Call) -> Optional[_FuncInfo]:
        fn = call.func
        if isinstance(fn, ast.Name):
            target = self.mod_funcs.get((fi.module, fn.id))
            if target is not None:
                return target
            imp = self.imports.get((fi.module, fn.id))
            if imp:
                return self.mod_funcs.get(imp)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fi.cls is not None:
                owner = self._resolve_class(fi.cls)
                if owner is not None:
                    target = owner.methods.get(fn.attr)
                    if target is not None:
                        return target
                return None
            # module-level singleton (e.g. transport_metrics.on_message):
            # resolve in THIS module or through its imports only — a
            # bare-name scan across all modules would bind same-named
            # singletons in unrelated modules to the wrong class
            cls_name = self.singletons.get((fi.module, base.id))
            if cls_name is None:
                imp = self.imports.get((fi.module, base.id))
                if imp:
                    cls_name = self.singletons.get(imp)
            if cls_name is not None:
                owner = self._resolve_class(cls_name)
                if owner is not None:
                    return owner.methods.get(fn.attr)
            return None
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and fi.cls is not None):
            owner = self._resolve_class(fi.cls)
            if owner is not None:
                attr_cls = (owner.attr_types.get(base.attr)
                            or EXTRA_ATTR_BINDINGS.get((fi.cls, base.attr)))
                if attr_cls:
                    target_cls = self._resolve_class(attr_cls)
                    if target_cls is not None:
                        return target_cls.methods.get(fn.attr)
        return None

    # -- transitive effects ----------------------------------------------

    def effects(self, fi: _FuncInfo) -> Tuple[Set[str], Set[str]]:
        """(locks acquired anywhere in fi or its callees, I/O descs
        reachable from fi). Computed as a TRUE fixpoint over the whole
        call graph — a memoized DFS that cuts recursion cycles would
        cache the cycle members' partial (often empty) effects and let
        real deadlocks through the gate."""
        if not self._effects:
            self._fixpoint()
        return self._effects.get(fi.qual, (set(), set()))

    def _fixpoint(self) -> None:
        funcs = list(self._all_funcs())
        callees: Dict[str, List[str]] = {}
        for fi in funcs:
            self._effects[fi.qual] = (
                {key for _, key, _ in fi.acquires},
                {desc for _, _, desc in fi.io})
            seen: Set[str] = set()
            for _, call, _ in fi.calls:
                target = self._resolve_call(fi, call)
                if target is not None and target.qual not in seen:
                    seen.add(target.qual)
                    callees.setdefault(fi.qual, []).append(target.qual)
        changed = True
        while changed:
            changed = False
            for fi in funcs:
                locks, io = self._effects[fi.qual]
                for callee in callees.get(fi.qual, ()):
                    t_locks, t_io = self._effects.get(callee, (set(), set()))
                    if not (t_locks <= locks and t_io <= io):
                        locks |= t_locks
                        io |= t_io
                        changed = True
                self._effects[fi.qual] = (locks, io)

    # -- analysis --------------------------------------------------------

    def _all_funcs(self):
        for infos in self.classes.values():
            for info in infos:
                yield from info.methods.values()
        yield from self.mod_funcs.values()

    def run(self) -> List[Finding]:
        findings: List[Finding] = []
        # edge: (src lock, dst lock) -> (path, line) of one witness site
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for fi in self._all_funcs():
            for held, key, line in fi.acquires:
                for h, h_line in held:
                    if h == key:
                        if self.lock_kind.get(h) != "rlock":
                            findings.append(Finding(
                                "lock-order", fi.module, line,
                                f"{fi.qual} re-acquires non-reentrant "
                                f"lock {key} while holding it — "
                                f"self-deadlock"))
                        continue
                    edges.setdefault((h, key), (fi.module, line))
            # I/O findings anchor at the ACQUISITION line of the held
            # lock so one justified pragma on the `with` covers the
            # whole region
            for held, line, desc in fi.io:
                for h, h_line in held:
                    findings.append(Finding(
                        "lock-io", fi.module, h_line,
                        f"{fi.qual} performs blocking I/O ({desc}, line "
                        f"{line}) while holding {h} — a stalled "
                        f"peer/volume pins the lock"))
            for held, call, line in fi.calls:
                if not held:
                    continue
                target = self._resolve_call(fi, call)
                if target is None:
                    continue
                t_locks, t_io = self.effects(target)
                for h, h_line in held:
                    for t in t_locks:
                        if t == h:
                            if self.lock_kind.get(h) != "rlock":
                                findings.append(Finding(
                                    "lock-order", fi.module, line,
                                    f"{fi.qual} holds {h} and calls "
                                    f"{target.qual} which re-acquires it "
                                    f"— self-deadlock (non-reentrant)"))
                            continue
                        edges.setdefault((h, t), (fi.module, line))
                    for desc in sorted(t_io):
                        findings.append(Finding(
                            "lock-io", fi.module, h_line,
                            f"{fi.qual} holds {h} across a call to "
                            f"{target.qual} (line {line}), which reaches "
                            f"blocking I/O ({desc})"))
        findings.extend(self._cycles(edges))
        return findings

    @staticmethod
    def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # iterative Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out: List[Finding] = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            # anchor the finding at one edge inside the cycle so a
            # pragma there (with a justification) can suppress it
            site = None
            for a, b in edges:
                if a in scc and b in scc:
                    site = edges[(a, b)]
                    break
            path, line = site if site else ("", 0)
            out.append(Finding(
                "lock-order", path, line,
                f"lock-order cycle (potential deadlock): "
                f"{' -> '.join(cyc)} -> {cyc[0]} — acquisition order "
                f"must be a DAG"))
        return out


def _ann_class(ann: Optional[ast.AST]) -> str:
    """Class name out of an annotation: Name, 'String', Optional[X]."""
    if ann is None:
        return ""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip('"\'')
        return name if name and name[0].isupper() else ""
    if isinstance(ann, ast.Name):
        return ann.id if ann.id[0].isupper() else ""
    if isinstance(ann, ast.Subscript):  # Optional[X] / List[X]
        return _ann_class(ann.slice)
    if isinstance(ann, ast.Attribute):
        return ann.attr if ann.attr[0].isupper() else ""
    return ""


def _stmt_exprs(st: ast.stmt) -> List[ast.AST]:
    """Expressions evaluated by this statement at its own nesting level
    (compound bodies are recursed separately)."""
    out: List[ast.AST] = []
    for f in ("value", "test", "iter", "exc", "msg", "target", "targets"):
        v = getattr(st, f, None)
        if isinstance(v, ast.AST):
            out.append(v)
        elif isinstance(v, list):
            out.extend(x for x in v if isinstance(x, ast.AST))
    return out


def _stmt_bodies(st: ast.stmt) -> List[List[ast.stmt]]:
    out: List[List[ast.stmt]] = []
    for f in ("body", "orelse", "finalbody"):
        v = getattr(st, f, None)
        if isinstance(v, list) and v and isinstance(v[0], ast.stmt):
            out.append(v)
    for h in getattr(st, "handlers", []) or []:
        out.append(h.body)
    return out


class LockOrderPass(AnalysisPass):
    """Framework adapter: run the analyzer over the concurrent-plane
    modules (or an explicit scope for fixture tests)."""

    id = "lock-order"  # emits lock-order AND lock-io findings
    description = ("lock-acquisition cycles and held-lock blocking I/O "
                   "across transport/gang/sched/serving/core")

    def __init__(self, scope: Sequence[str] = DEFAULT_SCOPE) -> None:
        self.scope = tuple(scope)

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        targets = [
            s for s in files
            if any(s.path.startswith(p) for p in self.scope)]
        if not targets:
            return []
        return LockOrderAnalyzer(targets).run()
