"""Guarded-transition model of the admitter/scheduler control plane.

This is the *protocol* half of the second verification tier
(docs/static_analysis.md "Protocol model"): the
TPUSliceAdmitter / CapacityScheduler / drain / elastic-resize /
slice-failure machine from ``gang/slice_admitter.py`` and
``sched/capacity.py``, re-stated as a small explicit-state transition
system that ``analysis/model.py`` can exhaustively explore.  The model
deliberately keeps the admitter's *dual bookkeeping* — a gang's
``granted`` list AND the per-slice ``owner`` field — so chip
conservation is a real cross-check, not a tautology: the invariant
catches exactly the partial-grant / double-book / drain-drift bugs
CHANGES.md shows were fixed by hand.

Abstractions (each mirrors a choke point in the real code):

* slices are uniform (1 chip each); hetero ROLE/stage gangs reduce to
  "N *distinct* slices, all-or-nothing", which is what
  ``_hetero_assign`` guarantees;
* pod deletion for revoked survivors of a slice failure is atomic with
  the revocation (the scheduler issues deletes synchronously before
  the admitter returns);
* grant selection is deterministic (lowest slice name) — the admitter's
  ``_pick_slices`` is deterministic too, and determinism here bounds
  the state space without losing interleavings;
* timestamps/deadlines become nondeterministic ``*_timeout``
  transitions: the checker explores "expired" at every reachable
  point, which over-approximates every real clock.

Transitions (ISSUE 17 list): grant, evict (drain-park or immediate
free), confirm_drain, release (pod exit; last exit enables
confirm_drain), slice_failed, resize_post (grow pre-grant),
resize_reply (live-reshard migrate), resize_timeout (fallback),
drain_timeout (grace expiry), pods_start, and restart — the operator
forgetting all in-memory state while pods keep running.  ``restart``
is OFF by default: with it on, the no-regrant-over-live-pod invariant
FAILS, and that counterexample trace is the pinned spec for the
ROADMAP item 5 grant journal (tests/test_protocol_model.py).

Bug toggles (``bug_partial_grant``, ``bug_no_shield``) re-introduce
two historical bug classes so the checker's counterexamples can be
unit-tested against a known-bad machine.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Iterable, Iterator, List, Optional, Tuple

__all__ = [
    "ProtocolError",
    "Slice",
    "Gang",
    "Drain",
    "State",
    "AdmitterModel",
    "INVARIANTS",
    "default_machine",
    "restart_machine",
    "journaled_restart_machine",
]


class ProtocolError(Exception):
    """A structural protocol violation raised *while applying* a
    transition (e.g. freeing an already-free slice).  The checker
    treats it as a counterexample, same as an invariant failure — this
    is how "drain releases exactly once" is enforced: every release
    funnels through :meth:`AdmitterModel._free`, which refuses a
    second free."""


# owner: "" (free) | "<gang>" (granted) | "drain:<gang>" (parked)
Slice = namedtuple("Slice", "name owner dead")
# need mutates on resize; hetero gangs need `need` *distinct* slices.
Gang = namedtuple("Gang", "key need prio hetero granted pods resizing")
# kind: evict | resize | failure; for_gang: beneficiary of an eviction
# ("" otherwise) — the no-eviction-storm invariant needs to know WHO
# the drain was supposed to help.
Drain = namedtuple("Drain", "gang kind for_gang")
State = namedtuple("State", "slices gangs drains")

_DRAIN = "drain:"


def _slice_names(st: State) -> List[str]:
    return [s.name for s in st.slices]


def _free_names(st: State) -> List[str]:
    return [s.name for s in st.slices if s.owner == "" and not s.dead]


def _alive_count(st: State) -> int:
    return sum(1 for s in st.slices if not s.dead)


def _gang(st: State, key: str) -> Gang:
    for g in st.gangs:
        if g.key == key:
            return g
    raise KeyError(key)


def _set_gang(st: State, g: Gang) -> State:
    return st._replace(
        gangs=tuple(g if x.key == g.key else x for x in st.gangs))


def _set_owner(st: State, name: str, owner: str) -> State:
    return st._replace(slices=tuple(
        s._replace(owner=owner) if s.name == name else s
        for s in st.slices))


def _mark_dead(st: State, name: str) -> State:
    return st._replace(slices=tuple(
        s._replace(dead=True) if s.name == name else s
        for s in st.slices))


def _drop_pod(st: State, name: str) -> State:
    """Remove `name` from every gang's pod set (pod killed/dead)."""
    return st._replace(gangs=tuple(
        g._replace(pods=frozenset(p for p in g.pods if p != name))
        if name in g.pods else g
        for g in st.gangs))


def _pods_on(st: State, name: str) -> bool:
    return any(name in g.pods for g in st.gangs)


class AdmitterModel:
    """The admitter/scheduler machine as ``initial()`` +
    ``successors(state)`` for :func:`kubedl_tpu.analysis.model.check`.

    ``gangs`` is a tuple of ``(key, need, prio, hetero)``.  Higher
    ``prio`` evicts lower, mirroring ``_reserve_waiting``'s
    ``(-priority, seq)`` order.
    """

    def __init__(
        self,
        n_slices: int = 3,
        gangs: Tuple[Tuple[str, int, int, bool], ...] = (
            ("a", 1, 2, False), ("b", 2, 1, True)),
        enable_restart: bool = False,
        enable_resize: bool = True,
        enable_failure: bool = True,
        journaled: bool = False,
        bug_partial_grant: bool = False,
        bug_no_shield: bool = False,
    ) -> None:
        self.n_slices = n_slices
        self.gang_specs = gangs
        self.enable_restart = enable_restart
        self.journaled = journaled
        self.enable_resize = enable_resize
        self.enable_failure = enable_failure
        self.bug_partial_grant = bug_partial_grant
        self.bug_no_shield = bug_no_shield

    # -- construction ----------------------------------------------------

    def initial(self) -> State:
        return State(
            slices=tuple(Slice(f"s{i}", "", False)
                         for i in range(self.n_slices)),
            gangs=tuple(Gang(k, need, prio, het, (), frozenset(), "")
                        for k, need, prio, het in self.gang_specs),
            drains=(),
        )

    def describe(self) -> str:
        gangs = ", ".join(
            f"{k}:need={need},prio={prio}{',hetero' if het else ''}"
            for k, need, prio, het in self.gang_specs)
        flags = []
        if self.enable_restart:
            flags.append("restart+journal" if self.journaled else "restart")
        if self.bug_partial_grant:
            flags.append("bug:partial-grant")
        if self.bug_no_shield:
            flags.append("bug:no-shield")
        tail = f" [{'+'.join(flags)}]" if flags else ""
        return f"{self.n_slices} slices x gangs({gangs}){tail}"

    # -- the exactly-once release choke point ----------------------------

    @staticmethod
    def _free(st: State, name: str) -> State:
        for s in st.slices:
            if s.name == name:
                if s.owner == "":
                    raise ProtocolError(
                        f"double release: slice {name} freed twice")
                return _set_owner(st, name, "")
        raise ProtocolError(f"release of unknown slice {name}")

    def _finish_drain(self, st: State, gang_key: str) -> State:
        """Free every ``drain:<gang>`` slice and drop the record —
        the model's ``_free_drained_slice``/``_finish_drain``."""
        for s in st.slices:
            if s.owner == _DRAIN + gang_key:
                st = self._free(st, s.name)
        remaining = tuple(d for d in st.drains if d.gang != gang_key)
        if len(remaining) == len(st.drains):
            raise ProtocolError(
                f"finish_drain for {gang_key} without a drain record")
        return st._replace(drains=remaining)

    # -- transitions -----------------------------------------------------

    def successors(self, st: State) -> Iterator[Tuple[str, State]]:
        free = _free_names(st)
        alive = _alive_count(st)

        # operator: grant — all-or-nothing over free slices, lowest
        # names first (deterministic _pick_slices analog)
        for g in st.gangs:
            if g.granted or g.resizing:
                continue
            if self.bug_partial_grant:
                take = tuple(free[:g.need])
                if take:
                    ns = st
                    for name in take:
                        ns = _set_owner(ns, name, g.key)
                    ns = _set_gang(ns, g._replace(granted=take))
                    yield f"grant({g.key})", ns
            elif len(free) >= g.need:
                take = tuple(free[:g.need])
                ns = st
                for name in take:
                    ns = _set_owner(ns, name, g.key)
                ns = _set_gang(ns, g._replace(granted=take))
                yield f"grant({g.key})", ns

        # executor: pods_start — pods come up on the granted slices
        for g in st.gangs:
            if g.granted and not g.pods and not g.resizing:
                ns = _set_gang(st, g._replace(pods=frozenset(g.granted)))
                yield f"pods_start({g.key})", ns

        # operator: evict(victim for beneficiary) — drain-park when
        # pods are live (fail closed), immediate free otherwise.  The
        # feasibility shield mirrors _reserve_waiting: only evict when
        # the beneficiary is feasible at all AND eviction actually
        # unblocks it.
        for victim in st.gangs:
            if not victim.granted or victim.resizing:
                continue
            if any(d.gang == victim.key for d in st.drains):
                continue
            for ben in st.gangs:
                if ben.key == victim.key or ben.granted or ben.resizing:
                    continue
                if ben.prio <= victim.prio:
                    continue
                if not self.bug_no_shield:
                    if ben.need > alive:          # infeasible: shielded
                        continue
                    if ben.need <= len(free):     # no eviction needed
                        continue
                    if ben.need > len(free) + len(victim.granted):
                        continue                  # eviction cannot help
                ns = st
                if victim.pods:
                    for name in victim.granted:
                        ns = _set_owner(ns, name, _DRAIN + victim.key)
                    ns = ns._replace(drains=ns.drains + (
                        Drain(victim.key, "evict", ben.key),))
                else:
                    for name in victim.granted:
                        ns = self._free(ns, name)
                ns = _set_gang(ns, _gang(ns, victim.key)._replace(
                    granted=()))
                yield f"evict({victim.key} for {ben.key})", ns

        # executor: release — one pod exits; frees nothing by itself
        # (the operator confirms via confirm_drain / drain_timeout)
        for g in st.gangs:
            for name in sorted(g.pods):
                ns = _set_gang(st, g._replace(
                    pods=frozenset(p for p in g.pods if p != name)))
                yield f"release({g.key}@{name})", ns

        # operator: confirm_drain — every pod on the parked slices has
        # exited (or migrated), so the drain finishes exactly once
        for d in st.drains:
            parked = [s.name for s in st.slices
                      if s.owner == _DRAIN + d.gang]
            if any(_pods_on(st, name) for name in parked):
                continue
            ns = self._finish_drain(st, d.gang)
            yield f"confirm_drain({d.gang})", ns

        # operator: drain_timeout — grace expiry kills the remaining
        # pods and frees the parked slices (the _expire_drains safety
        # valve; deadline-only drains can ONLY finish this way)
        for d in st.drains:
            ns = st
            for s in st.slices:
                if s.owner == _DRAIN + d.gang:
                    ns = _drop_pod(ns, s.name)
            ns = self._finish_drain(ns, d.gang)
            yield f"drain_timeout({d.gang})", ns

        # operator+pods: elastic resize, grow by one slice with the
        # grow pre-grant (new slices verified+granted BEFORE the old
        # ones drain — resize_to in evict_gang)
        if self.enable_resize:
            for g in st.gangs:
                if (not g.granted or g.resizing
                        or g.pods != frozenset(g.granted)):
                    continue
                if any(d.gang == g.key for d in st.drains):
                    continue
                new_need = g.need + 1
                if len(free) < new_need:
                    continue
                take = tuple(free[:new_need])
                ns = st
                for name in g.granted:
                    ns = _set_owner(ns, name, _DRAIN + g.key)
                for name in take:
                    ns = _set_owner(ns, name, g.key)
                ns = ns._replace(drains=ns.drains + (
                    Drain(g.key, "resize", ""),))
                ns = _set_gang(ns, _gang(ns, g.key)._replace(
                    need=new_need, granted=take, resizing="posted"))
                yield f"resize_post({g.key}->{new_need})", ns
            for g in st.gangs:
                if g.resizing != "posted":
                    continue
                # pods ack RESIZE with outcome=ok: live reshard moved
                # them to the new slices; confirm_drain then frees the
                # old ones (scheduler calls confirm_drain on ok)
                ns = _set_gang(st, g._replace(
                    pods=frozenset(g.granted), resizing=""))
                yield f"resize_reply({g.key} ok)", ns
                # no ack in time: checkpoint-restore fallback — old
                # pods are torn down, fresh pods_start on the new grant
                ns = _set_gang(st, g._replace(
                    pods=frozenset(), resizing=""))
                yield f"resize_timeout({g.key})", ns

        # environment: slice_failed — whole-gang revocation; the dead
        # slice parks as a deadline-only drain, survivors free with
        # their pod deletes issued synchronously
        if self.enable_failure:
            for s in st.slices:
                if s.dead:
                    continue
                ns = _mark_dead(st, s.name)
                if s.owner.startswith(_DRAIN):
                    ns = _drop_pod(ns, s.name)
                elif s.owner:
                    owner = _gang(ns, s.owner)
                    ns = _drop_pod(ns, s.name)
                    for name in owner.granted:
                        if name == s.name:
                            continue
                        ns = self._free(ns, name)
                        ns = _drop_pod(ns, name)
                    ns = _set_owner(ns, s.name, _DRAIN + owner.key)
                    if not any(d.gang == owner.key for d in ns.drains):
                        ns = ns._replace(drains=ns.drains + (
                            Drain(owner.key, "failure", ""),))
                    ns = _set_gang(ns, _gang(ns, owner.key)._replace(
                        granted=(), resizing=""))
                else:
                    ns = _drop_pod(ns, s.name)
                yield f"slice_failed({s.name})", ns

        # operator: restart — pods keep running because they are real
        # processes, and dead slices stay dead because the inventory
        # re-detects them.  WITHOUT the journal, ALL in-memory state is
        # forgotten (grants, drains, resize progress) and the
        # no-regrant-over-live-pod counterexample follows; WITH the
        # journal (kubedl_tpu/journal/wal.py), every transition above
        # was durably appended before its commit, so replay rebuilds
        # exactly the pre-crash bookkeeping.
        if self.enable_restart:
            if self.journaled:
                yield "restart(journal-replay)", self._replay(st)
            else:
                ns = State(
                    slices=tuple(s._replace(owner="") for s in st.slices),
                    gangs=tuple(g._replace(granted=(), resizing="")
                                for g in st.gangs),
                    drains=(),
                )
                yield "restart(operator)", ns

    def _replay(self, st: State) -> State:
        """Journaled restart: the write-ahead ordering (append+fsync
        BEFORE every in-memory commit) means the journal's effective
        state equals the pre-crash state, so replay is the identity on
        every reachable state — which is exactly what the checker
        proves by closing the same space as the restart-free machine.

        The conservative branch mirrors
        ``TPUSliceAdmitter.restore_from_journal``: if replay ever met a
        slice whose journaled grant conflicts with another gang's live
        pod (possible only with a corrupted journal — such a state
        already violates no-regrant-over-live-pod, so BFS can never
        reach it here), the whole reservation is withheld: conflicted
        slices park as a deadline-only drain, the rest free, the gang
        returns to waiting.  Never re-grant over a live pod."""
        ns = st
        for g in st.gangs:
            conflicted = [
                name for name in g.granted
                if any(name in o.pods for o in st.gangs if o.key != g.key)]
            if not conflicted:
                continue  # journal agrees with pod reality: keep as-is
            for name in g.granted:
                if name in conflicted:
                    ns = _set_owner(ns, name, _DRAIN + g.key)
                else:
                    ns = self._free(ns, name)
            if not any(d.gang == g.key for d in ns.drains):
                ns = ns._replace(drains=ns.drains + (
                    Drain(g.key, "failure", ""),))
            ns = _set_gang(ns, _gang(ns, g.key)._replace(
                granted=(), resizing=""))
        return ns


# ---------------------------------------------------------------------------
# invariants — each returns None (holds) or a violation message
# ---------------------------------------------------------------------------


def inv_chip_conservation(st: State) -> Optional[str]:
    """Dual-bookkeeping cross-check: every slice has at most one
    claimant, and gang.granted agrees with slice.owner both ways —
    granted + draining + free + dead partitions the pool."""
    claim = {}
    for g in st.gangs:
        if len(set(g.granted)) != len(g.granted):
            return (f"gang {g.key} granted list has duplicates: "
                    f"{g.granted}")
        for name in g.granted:
            if name in claim:
                return (f"slice {name} double-booked by gangs "
                        f"{claim[name]} and {g.key}")
            claim[name] = g.key
    names = set(_slice_names(st))
    for name in claim:
        if name not in names:
            return f"gang {claim[name]} granted unknown slice {name}"
    for s in st.slices:
        want = claim.get(s.name, "")
        if want and s.owner != want:
            return (f"slice {s.name}: granted to {want} but owner "
                    f"field says {s.owner!r}")
        if not want and s.owner and not s.owner.startswith(_DRAIN):
            return (f"slice {s.name}: owner field says {s.owner!r} "
                    f"but no gang's granted list contains it")
    draining = {s.owner[len(_DRAIN):]
                for s in st.slices if s.owner.startswith(_DRAIN)}
    recorded = {d.gang for d in st.drains}
    if draining != recorded:
        return (f"drain bookkeeping drift: slices parked for "
                f"{sorted(draining)} but records exist for "
                f"{sorted(recorded)}")
    return None


def inv_all_or_nothing(st: State) -> Optional[str]:
    for g in st.gangs:
        if len(g.granted) not in (0, g.need):
            return (f"partial admission: gang {g.key} holds "
                    f"{len(g.granted)}/{g.need} slices {g.granted}")
        if g.hetero and len(set(g.granted)) != len(g.granted):
            return (f"hetero gang {g.key} assigned the same slice to "
                    f"two stages: {g.granted}")
    return None


def inv_no_eviction_storm(st: State) -> Optional[str]:
    """An evict-drain must have a beneficiary whose demand can fit the
    pool at all — evicting a running gang for demand that can NEVER be
    admitted is a storm (work lost, nothing gained).  Judged against
    the pool size, not the momentary alive count: a slice dying AFTER
    a sound eviction decision does not make the decision a storm."""
    pool = len(st.slices)
    for d in st.drains:
        if d.kind != "evict":
            continue
        try:
            ben = _gang(st, d.for_gang)
        except KeyError:
            return (f"evict-drain of {d.gang} names unknown "
                    f"beneficiary {d.for_gang!r}")
        if ben.need > pool:
            return (f"eviction storm: {d.gang} evicted for "
                    f"{ben.key} which needs {ben.need} of a "
                    f"{pool}-slice pool (unsatisfiable)")
    return None


def inv_no_regrant_over_live_pod(st: State) -> Optional[str]:
    """The ROADMAP item 5 invariant: a slice must never be granted to
    one gang while another gang's pod is still running on it, and
    never granted at all while dead.  Fails under ``restart`` until
    the grant journal lands."""
    for g in st.gangs:
        for name in g.granted:
            for other in st.gangs:
                if other.key != g.key and name in other.pods:
                    return (
                        f"slice {name} granted to gang {g.key} while "
                        f"gang {other.key}'s pod still runs on it")
    for s in st.slices:
        if s.dead and s.owner and not s.owner.startswith(_DRAIN):
            return f"dead slice {s.name} granted to {s.owner}"
    return None


#: id -> checker function; the ids appear in counterexample traces,
#: docs/static_analysis.md, and the pinned-spec test.
INVARIANTS = {
    "chip-conservation": inv_chip_conservation,
    "all-or-nothing": inv_all_or_nothing,
    "no-eviction-storm": inv_no_eviction_storm,
    "no-regrant-over-live-pod": inv_no_regrant_over_live_pod,
}


def default_machine(**overrides) -> AdmitterModel:
    """HEAD machine: 3 slices, a hi-prio gang of 1 and a lo-prio
    hetero gang of 2, resize + failure on, restart OFF.  Passes every
    invariant (tests/test_protocol_model.py pins the state count)."""
    return AdmitterModel(**overrides)


def restart_machine(**overrides) -> AdmitterModel:
    """Same machine with operator ``restart`` enabled but NO journal —
    the no-regrant-over-live-pod invariant fails by a short trace.
    Originally the committed spec for the grant journal (ROADMAP
    item 5); now that ``kubedl_tpu/journal/`` exists it is kept as the
    seeded-bug control proving the checker still catches the
    journal-less restart."""
    overrides.setdefault("enable_restart", True)
    return AdmitterModel(**overrides)


def journaled_restart_machine(**overrides) -> AdmitterModel:
    """Restart WITH the write-ahead journal: replay reconstructs the
    pre-crash bookkeeping, so every invariant — including
    no-regrant-over-live-pod — is PROVED over the same state space as
    the restart-free machine (`make model-check` runs this)."""
    overrides.setdefault("enable_restart", True)
    overrides.setdefault("journaled", True)
    return AdmitterModel(**overrides)
