"""Explicit-state model checker (docs/static_analysis.md "Protocol
model").

Generic, stdlib-only, breadth-first: a *machine* is anything with
``initial() -> state`` and ``successors(state) -> iterable[(label,
state)]`` over hashable states; *invariants* are ``state -> None |
message``.  BFS (not DFS) so the first counterexample found is a
*shortest* one — counterexample traces double as specs
(tests/test_protocol_model.py pins the operator-restart trace as the
ROADMAP item 5 grant-journal spec), and a minimal trace is a readable
spec.  Exhaustiveness comes from the visited set: the admitter model
keeps no clocks or counters in its states, so the reachable space is
finite and the checker closes it (state count in ``Result.states``).

A :class:`~kubedl_tpu.analysis.protocol.ProtocolError` raised while
*applying* a transition counts as a counterexample too — that is how
structural one-shot rules ("drain releases exactly once") are checked
without encoding history into the state.

Entry points: ``kubedl-tpu analyze --model`` /
``python -m kubedl_tpu.analysis --model`` (see :func:`run_model` /
:func:`model_report`), ``make model-check``, and the tier-1 tests.
"""
from __future__ import annotations

from collections import deque, namedtuple
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from kubedl_tpu.analysis.protocol import (
    INVARIANTS,
    ProtocolError,
    State,
    default_machine,
    journaled_restart_machine,
    restart_machine,
)

__all__ = [
    "Result",
    "check",
    "render_state",
    "render_trace",
    "run_model",
    "model_report",
]

# trace: tuple of (label, state) from the initial state (label "" for
# the initial entry) to the violating state, inclusive.
Result = namedtuple(
    "Result", "ok states depth invariant violation trace truncated")


def check(
    machine,
    invariants: Optional[Dict[str, Callable]] = None,
    max_states: int = 2_000_000,
) -> Result:
    """Exhaustively explore ``machine`` breadth-first, checking every
    invariant at every reachable state.  Returns the shortest
    counterexample (by transition count) or ``ok=True`` with the
    closed state count.  ``truncated=True`` means ``max_states`` was
    hit before the space closed — treat that as a failed proof."""
    invs = INVARIANTS if invariants is None else invariants
    init = machine.initial()
    # state -> (parent_state, label); parent of init is None
    parents: Dict[object, Optional[Tuple[object, str]]] = {init: None}
    queue = deque([(init, 0)])
    max_depth = 0

    def trace_to(state, extra: Optional[Tuple[str, object]] = None):
        steps: List[Tuple[str, object]] = []
        cur = state
        while True:
            link = parents[cur]
            if link is None:
                break
            parent, label = link
            steps.append((label, cur))
            cur = parent
        steps.reverse()
        steps.insert(0, ("", cur))
        if extra is not None:
            steps.append(extra)
        return tuple(steps)

    def violated(state):
        for inv_id, fn in invs.items():
            msg = fn(state)
            if msg is not None:
                return inv_id, msg
        return None

    bad = violated(init)
    if bad is not None:
        return Result(False, 1, 0, bad[0], bad[1], trace_to(init), False)

    while queue:
        state, depth = queue.popleft()
        max_depth = max(max_depth, depth)
        try:
            succs = list(machine.successors(state))
        except ProtocolError as e:
            return Result(
                False, len(parents), depth, "protocol-structure", str(e),
                trace_to(state, ("<transition raised>", state)), False)
        for label, nxt in succs:
            if nxt in parents:
                continue
            parents[nxt] = (state, label)
            bad = violated(nxt)
            if bad is not None:
                return Result(
                    False, len(parents), depth + 1, bad[0], bad[1],
                    trace_to(nxt), False)
            if len(parents) >= max_states:
                return Result(
                    True, len(parents), max_depth, None, None, (), True)
            queue.append((nxt, depth + 1))
    return Result(True, len(parents), max_depth, None, None, (), False)


# ---------------------------------------------------------------------------
# rendering — counterexamples must read as transition traces
# ---------------------------------------------------------------------------


def render_state(state) -> str:
    if not isinstance(state, State):
        return repr(state)
    parts = []
    for s in state.slices:
        tag = "DEAD " if s.dead else ""
        parts.append(f"{s.name}={tag}{s.owner or 'free'}")
    for g in state.gangs:
        pods = ",".join(sorted(g.pods)) or "-"
        rz = f" resizing={g.resizing}" if g.resizing else ""
        parts.append(
            f"{g.key}[need={g.need} granted={','.join(g.granted) or '-'}"
            f" pods={pods}{rz}]")
    for d in state.drains:
        ben = f" for {d.for_gang}" if d.for_gang else ""
        parts.append(f"drain({d.gang},{d.kind}{ben})")
    return "  ".join(parts)


def render_trace(result: Result) -> str:
    """Human-readable counterexample: numbered transitions with the
    state after each, then the violated invariant."""
    if result.ok:
        return (f"all invariants hold over {result.states} states "
                f"(depth {result.depth})")
    out = [f"counterexample ({len(result.trace) - 1} transitions), "
           f"invariant [{result.invariant}]:"]
    for i, (label, state) in enumerate(result.trace):
        head = "initial" if i == 0 else f"{i}. {label}"
        out.append(f"  {head}")
        out.append(f"       {render_state(state)}")
    out.append(f"  VIOLATION: {result.violation}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# the two standard runs behind `analyze --model` / make model-check
# ---------------------------------------------------------------------------


def run_model() -> Tuple[bool, str]:
    """Run the standard configurations:

    1. the HEAD machine (2 gangs, then 3 gangs, restart off) must
       pass EVERY invariant over the exhaustively-closed state space;
    2. the journaled-restart machine (the write-ahead journal of
       ``kubedl_tpu/journal/`` replays every grant/drain on restart)
       must ALSO prove every invariant — no-regrant-over-live-pod
       included — over the same 2-gang and 3-gang spaces;
    3. the journal-less restart machine must still fail
       ``no-regrant-over-live-pod`` — kept as the seeded-bug control
       showing the checker catches the pre-journal restart.

    Returns ``(ok, report_text)``; ok means every outcome matched.
    """
    lines: List[str] = []
    ok = True

    _3gang = dict(
        n_slices=4,
        gangs=(("a", 1, 3, False), ("b", 2, 2, True),
               ("c", 2, 1, False)))
    proved = [
        ("admitter 2-gang", default_machine()),
        ("admitter 3-gang", default_machine(**_3gang)),
        ("admitter 2-gang journaled restart",
         journaled_restart_machine()),
        ("admitter 3-gang journaled restart",
         journaled_restart_machine(**_3gang)),
    ]
    for tag, m in proved:
        res = check(m)
        lines.append(f"protocol model [{tag}]: {m.describe()}")
        if res.truncated:
            ok = False
            lines.append(
                f"  FAIL: state space did not close within {res.states} "
                f"states — not a proof")
        elif res.ok:
            lines.append(
                f"  invariants {', '.join(sorted(INVARIANTS))}: "
                f"PROVED over {res.states} states (depth {res.depth})")
        else:
            ok = False
            lines.append(
                "  FAIL: " + render_trace(res).replace("\n", "\n  "))

    m2 = restart_machine()
    res2 = check(m2)
    lines.append(f"protocol model [admitter+restart]: {m2.describe()}")
    if res2.ok:
        ok = False
        lines.append(
            "  FAIL: expected the no-regrant-over-live-pod "
            "counterexample (operator restart without a grant journal "
            "re-grants a held slice) but every invariant held — the "
            "journal-less machine is the seeded-bug control; if it "
            "stopped failing, the checker lost the bug")
    elif res2.invariant != "no-regrant-over-live-pod":
        ok = False
        lines.append(
            f"  FAIL: expected invariant no-regrant-over-live-pod to "
            f"fail, got [{res2.invariant}]:")
        lines.append("  " + render_trace(res2).replace("\n", "\n  "))
    else:
        lines.append(
            "  EXPECTED counterexample (journal-less seeded-bug "
            "control; the journaled machines above prove the fix — "
            "tests/test_protocol_model.py):")
        lines.append("  " + render_trace(res2).replace("\n", "\n  "))
    return ok, "\n".join(lines)


def model_report() -> int:
    """CLI entry: print the model run, return a process exit code."""
    ok, text = run_model()
    print(text)
    return 0 if ok else 1


if __name__ == "__main__":  # `make model-check`
    import sys

    sys.exit(model_report())
