"""AST pass framework: pragmas, registry, report (docs/static_analysis.md).

Design goals, in order:

  1. *dependency-free* — stdlib ``ast`` only, so ``make lint`` runs on a
     bare container before any of the jax stack imports;
  2. *justified allowlists* — a pragma without a justification string is
     itself a finding; reviewers stopped re-litigating a site exactly
     when the "why" travels with the suppression;
  3. *one report shape* — every pass emits ``Finding`` rows, the runner
     renders them human-first and ``--json`` for tooling, and the exit
     code is the presubmit gate.

Pragma syntax (same line as the finding, or the line directly above;
shown without the leading comment hash so this very docstring does not
register as a pragma — the analyzer lints itself):

    kubedl-analysis: allow[pass-id] why this site is intentional

File-scoped (first 10 lines of the module, suppresses the whole file
for that pass; anywhere lower it takes NO effect and is flagged):

    kubedl-analysis: allow-file[pass-id] why this whole file is exempt

The broad-except pass additionally honors the repo's existing
``# noqa: BLE001 — justification`` idiom (see passes.BroadExceptPass).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

PRAGMA_RE = re.compile(
    r"#\s*kubedl-analysis:\s*allow(?P<scope>-file)?\[(?P<pass>[a-z0-9-]+)\]"
    r"\s*(?P<why>.*?)\s*$"
)
# how many leading lines may carry a file-scoped pragma
_FILE_PRAGMA_WINDOW = 10


@dataclass
class Finding:
    pass_id: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    justification: str = ""  # set when allowlisted
    allowlisted: bool = False

    def to_dict(self) -> Dict:
        return {
            "pass": self.pass_id,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "allowlisted": self.allowlisted,
            **({"justification": self.justification}
               if self.allowlisted else {}),
        }

    def render(self) -> str:
        tail = f"  [allowed: {self.justification}]" if self.allowlisted else ""
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}{tail}"


@dataclass
class SourceFile:
    """One parsed module the passes share (parse once, visit many)."""

    path: str  # repo-relative posix path
    abspath: str
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.text, node) or ""


class Pragmas:
    """Per-file pragma index: (pass_id, line) -> justification."""

    def __init__(self, source: SourceFile) -> None:
        # line -> {pass_id: justification}
        self._by_line: Dict[int, Dict[str, str]] = {}
        self._file_wide: Dict[str, str] = {}
        self.bad_pragma_lines: List[int] = []  # pragma with empty why
        # allow-file below the window: does NOT take effect file-wide,
        # and silently degrading it to a line pragma would hide the
        # author's mistake — flagged loudly instead
        self.misplaced_file_pragma_lines: List[int] = []
        for i, raw in enumerate(source.lines, start=1):
            m = PRAGMA_RE.search(raw)
            if not m:
                continue
            why = m.group("why").strip()
            if not why:
                # an unjustified pragma is NOT a suppression — it is a
                # finding of its own (pragma-justification)
                self.bad_pragma_lines.append(i)
                continue
            if m.group("scope"):
                if i <= _FILE_PRAGMA_WINDOW:
                    self._file_wide[m.group("pass")] = why
                else:
                    self.misplaced_file_pragma_lines.append(i)
            else:
                self._by_line.setdefault(i, {})[m.group("pass")] = why

    def lookup(self, pass_id: str, line: int) -> Optional[str]:
        """Justification when `line` is allowlisted for `pass_id`
        (pragma on the line itself or the line directly above), else
        None."""
        if pass_id in self._file_wide:
            return self._file_wide[pass_id]
        for ln in (line, line - 1):
            why = self._by_line.get(ln, {}).get(pass_id)
            if why is not None:
                return why
        return None


@dataclass
class RepoContext:
    """What a repo-level pass may need beyond the python files."""

    root: str
    docs: Dict[str, str] = field(default_factory=dict)  # relpath -> text

    def doc_text(self, relpath: str) -> str:
        if relpath not in self.docs:
            try:
                with open(os.path.join(self.root, relpath)) as f:
                    self.docs[relpath] = f.read()
            except OSError:
                self.docs[relpath] = ""
        return self.docs[relpath]


class AnalysisPass:
    """Base: run() over the full file set so repo-level passes (e.g.
    debug-vars-family) can correlate across files; per-file passes just
    loop. Pragma application happens in the runner, not here — passes
    report everything they see."""

    id = "base"
    description = ""

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# file discovery / loading
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def discover_files(root: str, include_tests: bool = True) -> List[str]:
    """Repo-relative paths of every analyzable python file: the
    ``kubedl_tpu`` package, ``bench.py``, ``hack/``, and ``tests/``
    (pass-specific scoping happens inside each pass)."""
    rels: List[str] = []
    tops = ["kubedl_tpu", "hack"] + (["tests"] if include_tests else [])
    for top in tops:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(
                        os.path.relpath(os.path.join(dirpath, fn), root)
                        .replace(os.sep, "/"))
    for single in ("bench.py",):
        if os.path.exists(os.path.join(root, single)):
            rels.append(single)
    return sorted(rels)


def load_source(root: str, rel: str) -> Tuple[Optional[SourceFile], Optional[Finding]]:
    abspath = os.path.join(root, rel)
    try:
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return None, Finding("parse-error", rel, 0, f"unreadable: {e}")
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        return None, Finding(
            "parse-error", rel, e.lineno or 0, f"syntax error: {e.msg}")
    return SourceFile(
        path=rel, abspath=abspath, text=text, tree=tree,
        lines=text.splitlines()), None


# ---------------------------------------------------------------------------
# runner + report
# ---------------------------------------------------------------------------


@dataclass
class Report:
    findings: List[Finding]  # unallowlisted — these fail the gate
    allowlisted: List[Finding]
    files_analyzed: int = 0
    passes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        return json.dumps({
            "ok": self.ok,
            "files_analyzed": self.files_analyzed,
            "passes": self.passes,
            "findings": [f.to_dict() for f in self.findings],
            "allowlisted": [f.to_dict() for f in self.allowlisted],
        }, indent=1, sort_keys=True)

    def to_text(self) -> str:
        out: List[str] = []
        by_pass: Dict[str, List[Finding]] = {}
        for f in self.findings:
            by_pass.setdefault(f.pass_id, []).append(f)
        for pass_id in sorted(by_pass):
            out.append(f"== {pass_id} ({len(by_pass[pass_id])}) ==")
            out.extend(f.render() for f in by_pass[pass_id])
        out.append(
            f"{len(self.findings)} finding(s), "
            f"{len(self.allowlisted)} allowlisted, "
            f"{self.files_analyzed} files, "
            f"passes: {', '.join(self.passes)}")
        return "\n".join(out)


def default_passes() -> List[AnalysisPass]:
    # imported lazily so framework stays importable without the passes
    # (and the passes can import the framework)
    from kubedl_tpu.analysis.contracts import (
        CrashConsistencyPass,
        EnvContractPass,
        WireSchemaPass,
    )
    from kubedl_tpu.analysis.lockorder import LockOrderPass
    from kubedl_tpu.analysis.passes import (
        BenchLaneMergePass,
        BroadExceptPass,
        DebugVarsFamilyPass,
        PayloadDtypePass,
        PromEscapePass,
        SharedValidationPass,
    )

    return [
        PromEscapePass(),
        DebugVarsFamilyPass(),
        SharedValidationPass(),
        PayloadDtypePass(),
        BroadExceptPass(),
        BenchLaneMergePass(),
        LockOrderPass(),
        EnvContractPass(),
        WireSchemaPass(),
        CrashConsistencyPass(),
    ]


def run_analysis(
    root: str,
    passes: Optional[List[AnalysisPass]] = None,
    files: Optional[List[str]] = None,
    include_tests: bool = True,
) -> Report:
    """Run every pass over the tree; split findings into gate-failing vs
    pragma-allowlisted. ``files`` overrides discovery (tests feed
    fixture snippets through the real runner this way)."""
    passes = default_passes() if passes is None else passes
    rels = discover_files(root, include_tests) if files is None else files
    sources: List[SourceFile] = []
    findings: List[Finding] = []
    pragmas: Dict[str, Pragmas] = {}
    for rel in rels:
        src, err = load_source(root, rel)
        if err is not None:
            findings.append(err)
            continue
        sources.append(src)
        pragmas[src.path] = Pragmas(src)
        for ln in pragmas[src.path].bad_pragma_lines:
            findings.append(Finding(
                "pragma-justification", src.path, ln,
                "allowlist pragma carries no justification — say WHY the "
                "site is intentional"))
        for ln in pragmas[src.path].misplaced_file_pragma_lines:
            findings.append(Finding(
                "pragma-justification", src.path, ln,
                f"allow-file pragma must appear in the first "
                f"{_FILE_PRAGMA_WINDOW} lines of the module — here it "
                f"would suppress NOTHING file-wide; move it up or use a "
                f"line pragma"))
    ctx = RepoContext(root=root)
    for p in passes:
        findings.extend(p.run(sources, ctx))
    gate: List[Finding] = []
    allowed: List[Finding] = []
    for f in findings:
        why = None
        pr = pragmas.get(f.path)
        if pr is not None and f.pass_id not in (
                "pragma-justification", "parse-error"):
            why = pr.lookup(f.pass_id, f.line)
        if why is not None:
            f.allowlisted, f.justification = True, why
            allowed.append(f)
        else:
            gate.append(f)
    gate.sort(key=lambda f: (f.pass_id, f.path, f.line))
    allowed.sort(key=lambda f: (f.pass_id, f.path, f.line))
    return Report(
        findings=gate, allowlisted=allowed, files_analyzed=len(sources),
        passes=[p.id for p in passes])
