"""Opt-in runtime lock witness — the dynamic half of the lock-order
analysis (docs/static_analysis.md).

The static pass (lockorder.py) proves which inversions are POSSIBLE;
this witness records the acquisition orders a real run actually takes
and fails LOUDLY the moment two locks are ever taken in both orders —
the Python port's stand-in for the Go reference's ``-race`` habit,
exercised by the chaos/e2e lanes.

Product classes construct their locks through ``new_lock(name)`` /
``new_rlock(name)``. With ``KUBEDL_LOCK_WITNESS`` unset (the default,
and every production path) these return plain ``threading.Lock`` /
``RLock`` — zero wrapping, zero overhead. With the env var set at lock
construction time, locks are wrapped to:

  * keep a per-thread stack of held witness locks;
  * record every (held, acquired) NAME pair into a global order graph;
  * on acquiring B while holding A when B->A was already observed
    (any thread, any time in this process), record the inversion AND
    raise RuntimeError at the acquisition site — an inverted order is a
    deadlock waiting for the right interleaving, and the test must see
    it even if this run got lucky;
  * reentrant re-acquisition of the SAME lock object records nothing
    (RLock semantics); two INSTANCES sharing a name record a self-edge
    but never an inversion (instances are not statically orderable).

``KUBEDL_LOCK_WITNESS_DIR`` makes the process dump its observed edges +
inversions as JSON at exit (one file per pid), so the two-process
transport/RL e2e tests can assert the fleet ran inversion-free.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

ENV_WITNESS = "KUBEDL_LOCK_WITNESS"
ENV_WITNESS_DIR = "KUBEDL_LOCK_WITNESS_DIR"


def enabled() -> bool:
    return os.environ.get(ENV_WITNESS, "") not in ("", "0")


class LockInversion(RuntimeError):
    """Two locks observed in both acquisition orders — a deadlock
    waiting for the right interleaving."""


class _Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (first, then) -> times observed
        self._edges: Dict[Tuple[str, str], int] = {}
        self._inversions: List[Dict] = []
        self._tls = threading.local()
        self._dump_registered = False

    def _held(self) -> List[Tuple[str, int]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def on_acquired(self, name: str, obj_id: int) -> Optional[Dict]:
        """Record the acquisition; returns the inversion record (also
        stored) when this order contradicts one already observed. The
        CALLER raises — after releasing the just-acquired inner lock,
        so a failing background thread fails loudly instead of leaving
        the lock held forever and hanging shutdown."""
        held = self._held()
        if any(oid == obj_id for _, oid in held):
            # reentrant re-acquisition of the same object (RLock):
            # still push so releases balance, but record no edges
            held.append((name, obj_id))
            return None
        inversion: Optional[Dict] = None
        with self._lock:
            for h_name, h_oid in held:
                if h_name == name:
                    continue  # sibling instances are not orderable
                self._edges[(h_name, name)] = (
                    self._edges.get((h_name, name), 0) + 1)
                if (name, h_name) in self._edges and inversion is None:
                    inversion = {
                        "first": h_name, "then": name,
                        "thread": threading.current_thread().name,
                    }
                    self._inversions.append(inversion)
        held.append((name, obj_id))
        return inversion

    def on_released(self, obj_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == obj_id:
                del held[i]
                return

    def report(self) -> Dict:
        with self._lock:
            return {
                "edges": sorted([a, b] for (a, b) in self._edges),
                "inversions": list(self._inversions),
            }

    def reset(self) -> None:
        """Test isolation: drop the graph AND this thread's held stack
        (belt for tests that abandon locks mid-assertion)."""
        with self._lock:
            self._edges.clear()
            self._inversions.clear()
        self._tls.held = []

    def maybe_register_dump(self) -> None:
        out_dir = os.environ.get(ENV_WITNESS_DIR, "")
        if not out_dir or self._dump_registered:
            return
        self._dump_registered = True
        atexit.register(self._dump, out_dir)

    def _dump(self, out_dir: str) -> None:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"witness-{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.report(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # a lost report only weakens the assertion, the
            # inversion itself already raised at the acquisition site


registry = _Registry()


class WitnessLock:
    """Wraps a real lock; usable everywhere ``threading.Lock``/``RLock``
    is (context manager, acquire/release, Condition-compatible)."""

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            inv = registry.on_acquired(self._name, id(self))
            if inv is not None:
                # fail LOUDLY but not wedged: release what we just took
                # (and its held-stack entry) before raising, or an
                # inversion on a daemon thread would leave the lock held
                # forever and turn the loud failure into a shutdown hang
                registry.on_released(id(self))
                self._inner.release()
                self._raise(inv)
        return got

    @staticmethod
    def _raise(inv: Dict) -> None:
        raise LockInversion(
            f"lock order inversion: acquired {inv['then']!r} while "
            f"holding {inv['first']!r}, but the opposite order was also "
            f"observed in this process — a deadlock waiting for the "
            f"right interleaving")

    def release(self) -> None:
        self._inner.release()
        registry.on_released(id(self))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition() interop: threading.Condition probes the lock for
    # _release_save/_acquire_restore/_is_owned with try/except
    # AttributeError and falls back to plain release()/acquire() when
    # absent. These must therefore exist ONLY when the inner lock has
    # them (RLock) — a method defined unconditionally would make a
    # Condition over a witnessed plain Lock crash at wait() time, and
    # only in the witness-enabled chaos lanes.
    def __getattr__(self, name: str):
        if name == "_is_owned":
            return self._inner._is_owned  # AttributeError on plain Lock
        if name == "_release_save":
            inner_rs = self._inner._release_save

            def _release_save():
                state = inner_rs()
                registry.on_released(id(self))
                return state

            return _release_save
        if name == "_acquire_restore":
            inner_ar = self._inner._acquire_restore

            def _acquire_restore(state):
                inner_ar(state)
                inv = registry.on_acquired(self._name, id(self))
                if inv is not None:
                    registry.on_released(id(self))
                    self._inner.release()
                    self._raise(inv)

            return _acquire_restore
        raise AttributeError(name)


def new_lock(name: str):
    """A ``threading.Lock`` — witness-wrapped when KUBEDL_LOCK_WITNESS
    is set at construction time. `name` identifies the lock CLASS-wide
    (``module.Class.attr``), matching the static pass's lock keys."""
    if not enabled():
        return threading.Lock()
    registry.maybe_register_dump()
    return WitnessLock(threading.Lock(), name)


def new_rlock(name: str):
    """A ``threading.RLock`` — witness-wrapped when KUBEDL_LOCK_WITNESS
    is set at construction time."""
    if not enabled():
        return threading.RLock()
    registry.maybe_register_dump()
    return WitnessLock(threading.RLock(), name)
