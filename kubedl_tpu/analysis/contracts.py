"""Cross-process contract passes (docs/static_analysis.md):

  env-contract      — the injection→consumption graph of every
                      ``KUBEDL_*`` env var: the executor/workloads
                      layer injects, trainers/runtimes consume, the
                      docs env tables document.  Flags orphan
                      injections (set but never read), orphan
                      consumptions (read but never set AND not
                      documented as a user knob), undocumented
                      injections, and — the stale direction — doc
                      table entries matching nothing in code.
  wire-schema       — per transport channel family (RESIZE control,
                      resize replies, pipeline boundary, RL
                      trajectory/weights, staged-reshard blocks, KV
                      handoff): header keys and tag formats the
                      receiver reads must be keys the sender writes.
                      The cross-process analog of shared-validation:
                      the python in two pods never shares a type, so
                      the wire dict IS the schema.
  crash-consistency — every write to a durable path (control dir,
                      staging dir, trace dir, heartbeat,
                      ``.bench_extras.json``) must be atomic-rename
                      (tmp + ``os.replace`` / a ``*atomic*`` helper /
                      append-only JSONL), and a manifest must publish
                      AFTER its payload files — the manifest is the
                      commit point.

All three over-approximate on the permissive side where the code is
dynamic (f-string env names count as prefix injections; any string
occurrence of a var counts as consumption; only constant header keys
are checked) — a pass that cries wolf gets allowlisted into silence,
so drift detection errs toward fewer, real findings.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubedl_tpu.analysis.framework import (
    AnalysisPass,
    Finding,
    RepoContext,
    SourceFile,
)


def _in_tests(path: str) -> bool:
    return path.startswith("tests/")


def _sub_key(node: ast.Subscript):
    """The subscript key expression (3.8 ast.Index compatible)."""
    sl = node.slice
    if sl.__class__.__name__ == "Index":  # py3.8
        sl = sl.value  # type: ignore[attr-defined]
    return sl


def _is_os_environ(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


# ---------------------------------------------------------------------------
# env-contract
# ---------------------------------------------------------------------------

_ENV_TOKEN_RE = re.compile(r"KUBEDL_[A-Z0-9_]+")
# docs tokens additionally allow one {A,B,...} brace group, a trailing
# * wildcard, and A/B/C slash alternation, e.g.
# KUBEDL_RL_{GROUP_SIZE,ENGINE}, KUBEDL_CHECKPOINT_*, or
# KUBEDL_SERVING_SLOTS/MAX_LEN/KV_BLOCKS
_DOC_TOKEN_RE = re.compile(
    r"KUBEDL_[A-Z0-9_]*(?:\{[A-Z0-9_, ]+\})?[A-Z0-9_]*\*?"
    r"(?:/[A-Z0-9_]+\*?)*")


def _expand_doc_token(tok: str) -> Tuple[Set[str], Set[str]]:
    """A docs table token -> (exact var names, documented prefixes)."""
    # slash shorthand first: alternates share the FIRST name's prefix up
    # to its last underscore (KUBEDL_SERVING_SLOTS/MAX_LEN documents
    # KUBEDL_SERVING_SLOTS and KUBEDL_SERVING_MAX_LEN)
    segs = tok.split("/")
    stem = segs[0][: segs[0].rfind("_") + 1]
    pre = [segs[0]] + [stem + s for s in segs[1:]]
    names: List[str] = []
    for nm in pre:
        if "{" in nm and "}" in nm:
            head, rest = nm.split("{", 1)
            alts, tail = rest.split("}", 1)
            names.extend(head + a.strip() + tail for a in alts.split(","))
        else:
            names.append(nm)
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for n in names:
        if n.endswith("*"):
            prefixes.add(n[:-1])
        elif n.endswith("_"):
            prefixes.add(n)
        else:
            exact.add(n)
    return exact, prefixes


class EnvContractPass(AnalysisPass):
    """Injection→consumption→documentation contract for KUBEDL_* env.

    *Injection* = a constant ``d["KUBEDL_<name>"] = v`` / ``d.setdefault(
    "KUBEDL_<name>", v)`` store into an env dict anywhere outside tests
    (``os.environ`` stores are a process configuring ITSELF — that is
    consumption-side), plus dict-literal keys inside the injector
    layer (``kubedl_tpu/executor/``, ``kubedl_tpu/workloads/``), plus
    f-string keys with a constant ``KUBEDL_`` head (prefix injection,
    e.g. ``KUBEDL_LABEL_*``).  *Consumption* = any other string
    occurrence of the var in non-test code — reads go through
    ``environ.get``, named ``ENV_*`` constants and ``_env_int``-style
    helpers, and chasing dataflow is not worth false findings.
    *Documented* = the var (or a covering ``FOO_*`` prefix, with
    ``{A,B}`` brace groups expanded) appears in README.md or any
    docs/*.md.  The stale direction re-checks the three env-table docs
    (jaxjob/transport/pipeline) token by token against code.
    """

    id = "env-contract"
    description = ("KUBEDL_* env vars: orphan injections/consumptions, "
                   "missing or stale docs env-table entries")

    _INJECTOR_DIRS = ("kubedl_tpu/executor/", "kubedl_tpu/workloads/")
    _TABLE_DOCS = ("docs/jaxjob.md", "docs/transport.md",
                   "docs/pipeline.md")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        inject: Dict[str, Tuple[str, int]] = {}
        inject_prefix: Dict[str, Tuple[str, int]] = {}
        consumed: Dict[str, Tuple[str, int]] = {}
        for src in files:
            if _in_tests(src.path):
                continue
            key_ids = self._collect_injections(
                src, inject, inject_prefix)
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and id(node) not in key_ids):
                    for tok in _ENV_TOKEN_RE.findall(node.value):
                        if tok.endswith("_"):
                            continue  # prose prefix mention, not a var
                        consumed.setdefault(
                            tok, (src.path, node.lineno))

        doc_exact, doc_prefix = self._documented(ctx)
        out: List[Finding] = []

        def documented(var: str) -> bool:
            return (var in doc_exact
                    or any(var.startswith(p) for p in doc_prefix))

        for var in sorted(inject):
            path, line = inject[var]
            if var not in consumed:
                out.append(Finding(
                    self.id, path, line,
                    f"orphan injection: {var} is set on pods but no "
                    f"non-test code reads it — wire a consumer or drop "
                    f"the injection"))
            if not documented(var):
                out.append(Finding(
                    self.id, path, line,
                    f"undocumented injection: {var} is missing from the "
                    f"docs env tables (docs/jaxjob.md etc.)"))
        for prefix in sorted(inject_prefix):
            path, line = inject_prefix[prefix]
            if not (prefix in doc_prefix
                    or any(e.startswith(prefix) for e in doc_exact)):
                out.append(Finding(
                    self.id, path, line,
                    f"undocumented injection: dynamic {prefix}* vars are "
                    f"missing from the docs env tables — document the "
                    f"prefix (e.g. `{prefix}*`)"))

        def injected(var: str) -> bool:
            return (var in inject
                    or any(var.startswith(p) for p in inject_prefix))

        for var in sorted(consumed):
            if injected(var) or documented(var):
                continue
            path, line = consumed[var]
            out.append(Finding(
                self.id, path, line,
                f"orphan consumption: {var} is read here but nothing "
                f"injects it and no docs env table documents it as a "
                f"user-set knob"))

        known_exact = set(inject) | set(consumed)
        out.extend(self._stale_docs(ctx, known_exact, set(inject_prefix)))
        return out

    def _collect_injections(
        self,
        src: SourceFile,
        inject: Dict[str, Tuple[str, int]],
        inject_prefix: Dict[str, Tuple[str, int]],
    ) -> Set[int]:
        """Record injection sites; return ids of the key Constant nodes
        so the consumption scan does not count a var's own injection."""
        key_ids: Set[int] = set()
        in_injector = src.path.startswith(self._INJECTOR_DIRS)

        def record_key(key: ast.AST) -> None:
            if (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and _ENV_TOKEN_RE.fullmatch(key.value)):
                key_ids.add(id(key))
                inject.setdefault(key.value, (src.path, key.lineno))
            elif (isinstance(key, ast.JoinedStr) and key.values
                    and isinstance(key.values[0], ast.Constant)
                    and isinstance(key.values[0].value, str)
                    and key.values[0].value.startswith("KUBEDL_")):
                head = key.values[0]
                key_ids.add(id(head))
                inject_prefix.setdefault(
                    head.value, (src.path, key.lineno))

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and not _is_os_environ(t.value)):
                        record_key(_sub_key(t))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "setdefault"
                    and not _is_os_environ(node.func.value)
                    and node.args):
                record_key(node.args[0])
            elif isinstance(node, ast.Dict) and in_injector:
                for key in node.keys:
                    if key is not None:
                        record_key(key)
        return key_ids

    @staticmethod
    def _doc_paths(ctx: RepoContext) -> List[str]:
        rels = []
        if os.path.exists(os.path.join(ctx.root, "README.md")):
            rels.append("README.md")
        docs = os.path.join(ctx.root, "docs")
        for dirpath, _dirnames, filenames in os.walk(docs):
            for fn in sorted(filenames):
                if fn.endswith(".md"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, fn), ctx.root)
                        .replace(os.sep, "/"))
        return rels

    def _documented(self, ctx: RepoContext) -> Tuple[Set[str], Set[str]]:
        exact: Set[str] = set()
        prefixes: Set[str] = set()
        for rel in self._doc_paths(ctx):
            for tok in _DOC_TOKEN_RE.findall(ctx.doc_text(rel)):
                e, p = _expand_doc_token(tok)
                exact |= e
                prefixes |= p
        return exact, prefixes

    def _stale_docs(
        self,
        ctx: RepoContext,
        known_exact: Set[str],
        known_prefix: Set[str],
    ) -> List[Finding]:
        """Every KUBEDL_* token in the env-table docs must still exist
        in code.  Doc findings are not pragma-able — fix the doc."""
        out: List[Finding] = []

        def known(var: str) -> bool:
            return (var in known_exact
                    or any(var.startswith(p) for p in known_prefix))

        for rel in self._TABLE_DOCS:
            text = ctx.doc_text(rel)
            for i, line in enumerate(text.splitlines(), start=1):
                for tok in _DOC_TOKEN_RE.findall(line):
                    exact, prefixes = _expand_doc_token(tok)
                    for var in sorted(exact):
                        if not known(var):
                            out.append(Finding(
                                self.id, rel, i,
                                f"stale docs entry: {var} matches no "
                                f"injection or consumption in code"))
                    for p in sorted(prefixes):
                        if not (p in known_prefix
                                or any(v.startswith(p)
                                       for v in known_exact)):
                            out.append(Finding(
                                self.id, rel, i,
                                f"stale docs entry: prefix {p}* matches "
                                f"no injection or consumption in code"))
        return out


# ---------------------------------------------------------------------------
# wire-schema
# ---------------------------------------------------------------------------

_IDENT_KEY_RE = re.compile(r"[a-z_][a-z0-9_]*\Z")
_TAG_RE = re.compile(r"[A-Za-z0-9_.:{}\-]+\Z")

# (path, function names, mode) — mode "all": every identifier-like
# string constant plus reply-kwargs counts as written; mode "reply":
# ONLY keyword names of .reply(**kw) calls (the trainer's reply
# payload rides kwargs, and its enclosing functions are huge).
_W = Tuple[str, Tuple[str, ...], str]
# (path, function names, receiver variable names)
_R = Tuple[str, Tuple[str, ...], Tuple[str, ...]]

_FAMILIES: List[Dict] = [
    {
        "id": "resize-control",
        "writers": [
            ("kubedl_tpu/sched/capacity.py", ("_post_resize",), "all"),
            ("kubedl_tpu/transport/control.py", ("post",), "all"),
            ("kubedl_tpu/executor/local.py", ("post_control",), "all"),
        ],
        "readers": [
            ("kubedl_tpu/train/trainer.py", ("handle_resize", "main"),
             ("msg", "cmsg")),
            ("kubedl_tpu/train/reshard_runtime.py", ("poll",), ("msg",)),
            ("kubedl_tpu/transport/control.py", ("reply",), ("msg",)),
        ],
    },
    {
        "id": "resize-reply",
        "writers": [
            ("kubedl_tpu/train/trainer.py",
             ("_resize_fallback", "_resize_staged", "handle_resize",
              "main"), "reply"),
        ],
        "readers": [
            ("kubedl_tpu/sched/capacity.py", ("_reshard_pass",),
             ("r", "bad")),
        ],
    },
    {
        "id": "pipeline-boundary",
        "writers": [
            ("kubedl_tpu/parallel/pipeline_mpmd.py",
             ("encode_boundary",), "all"),
        ],
        "readers": [
            ("kubedl_tpu/parallel/pipeline_mpmd.py",
             ("decode_boundary",), ("header",)),
        ],
    },
    {
        "id": "rl-trajectory",
        "writers": [
            ("kubedl_tpu/rl/trajectory.py",
             ("encode_trajectory", "send"), "all"),
        ],
        "readers": [
            ("kubedl_tpu/rl/trajectory.py",
             ("decode_trajectory", "take"), ("meta", "arrays")),
        ],
    },
    {
        "id": "rl-weights",
        "writers": [
            ("kubedl_tpu/rl/weights.py",
             ("encode_weights", "publish"), "all"),
        ],
        "readers": [
            ("kubedl_tpu/rl/weights.py",
             ("decode_weights", "poll"), ("meta",)),
        ],
    },
    {
        "id": "weights-dist",
        "writers": [
            ("kubedl_tpu/weights/dist.py",
             ("encode_announce", "encode_manifest", "_reparent_request",
              "announce_tag", "chunk_tag", "manifest_tag",
              "reparent_tag", "commit_tag"), "all"),
        ],
        "readers": [
            ("kubedl_tpu/weights/dist.py",
             ("decode_announce", "decode_manifest", "_take_reparent"),
             ("header", "req")),
        ],
    },
    {
        "id": "reshard-blocks",
        "writers": [
            ("kubedl_tpu/transport/blocks.py",
             ("serve_staging", "on_request", "_fetch_one"), "all"),
            ("kubedl_tpu/train/reshard_runtime.py",
             ("stage_shards", "write_manifest"), "all"),
        ],
        "readers": [
            ("kubedl_tpu/transport/blocks.py",
             ("on_request", "_fetch_one", "fetch_staging"),
             ("req", "header", "manifest")),
            ("kubedl_tpu/train/reshard_runtime.py",
             ("staging_exists", "state_from_staging"),
             ("manifest", "info")),
        ],
    },
    {
        "id": "kv-handoff",
        "writers": [
            ("kubedl_tpu/serving/handoff.py", ("serialize_item",), "all"),
        ],
        "readers": [
            ("kubedl_tpu/serving/handoff.py",
             ("deserialize_item", "rows"), ("z",)),
        ],
        # the per-layer KV arrays ride dynamic k{i}/v{i} keys; only the
        # dtype probe reads the constant "k0"/"v0" spelling
        "extra_written": ("k0", "v0"),
    },
]


def _skeleton(js: ast.JoinedStr) -> Optional[str]:
    """Normalize an f-string to its tag skeleton: constants verbatim,
    interpolations as ``{}`` keeping the format spec (``{:08d}``)."""
    parts: List[str] = []
    for v in js.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue):
            spec = ""
            if v.format_spec is not None:
                sub = []
                for s in v.format_spec.values:
                    if not isinstance(s, ast.Constant):
                        return None
                    sub.append(str(s.value))
                spec = ":" + "".join(sub)
            parts.append("{" + spec + "}")
        else:
            return None
    return "".join(parts)


class WireSchemaPass(AnalysisPass):
    """Sender/receiver header-key and tag-format drift, per channel
    family.  The family table is declarative; a scope that no longer
    resolves (file or function renamed) is itself a finding so the
    table cannot rot silently.  Gate direction: a key READ by the
    receiver must be WRITTEN somewhere on the sender side (write-
    never-read is legal — debug fields ride replies).  Tag skeletons
    (compact f-strings, e.g. ``w.{:08d}``) read by consumers must
    match a producer skeleton."""

    id = "wire-schema"
    description = ("transport channel families: receiver header "
                   "keys/tag formats must match what senders write")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        by_path = {src.path: src for src in files}
        out: List[Finding] = []
        for fam in _FAMILIES:
            written: Set[str] = set(fam.get("extra_written", ()))
            wtags: Set[str] = set()
            for path, funcs, mode in fam["writers"]:
                scopes = self._resolve(by_path, path, funcs, fam, out)
                for fn in scopes:
                    w, t = self._collect_writes(fn, mode)
                    written |= w
                    wtags |= t
            for path, funcs, receivers in fam["readers"]:
                scopes = self._resolve(by_path, path, funcs, fam, out)
                for fn in scopes:
                    reads, rtags = self._collect_reads(fn, receivers)
                    for key, line in sorted(reads):
                        if key not in written:
                            out.append(Finding(
                                self.id, path, line,
                                f"[{fam['id']}] receiver reads key "
                                f"{key!r} that no sender in the family "
                                f"writes — schema drift"))
                    for sk, line in sorted(rtags):
                        if sk not in wtags:
                            out.append(Finding(
                                self.id, path, line,
                                f"[{fam['id']}] receiver expects tag "
                                f"format {sk!r} but producers write "
                                f"{sorted(wtags) or 'none'} — tag drift"))
        return out

    def _resolve(
        self,
        by_path: Dict[str, SourceFile],
        path: str,
        funcs: Sequence[str],
        fam: Dict,
        out: List[Finding],
    ) -> List[ast.AST]:
        src = by_path.get(path)
        if src is None:
            out.append(Finding(
                self.id, path, 0,
                f"[{fam['id']}] family table names missing module "
                f"{path} — update _FAMILIES in analysis/contracts.py"))
            return []
        found: List[ast.AST] = []
        seen: Set[str] = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in funcs):
                found.append(node)
                seen.add(node.name)
        for name in funcs:
            if name not in seen:
                out.append(Finding(
                    self.id, path, 1,
                    f"[{fam['id']}] family table names function "
                    f"{name}() which no longer exists in {path} — "
                    f"update _FAMILIES in analysis/contracts.py"))
        return found

    @staticmethod
    def _collect_writes(fn: ast.AST, mode: str) -> Tuple[Set[str], Set[str]]:
        keys: Set[str] = set()
        tags: Set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "reply"):
                for kw in node.keywords:
                    if kw.arg:
                        keys.add(kw.arg)
            if mode == "all":
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and _IDENT_KEY_RE.fullmatch(node.value)):
                    keys.add(node.value)
                if isinstance(node, ast.JoinedStr):
                    sk = _skeleton(node)
                    if sk and "{" in sk and _TAG_RE.fullmatch(sk):
                        tags.add(sk)
        return keys, tags

    @staticmethod
    def _collect_reads(
        fn: ast.AST, receivers: Sequence[str],
    ) -> Tuple[Set[Tuple[str, int]], Set[Tuple[str, int]]]:
        def from_receiver(expr: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id in receivers
                       for n in ast.walk(expr))

        reads: Set[Tuple[str, int]] = set()
        tags: Set[Tuple[str, int]] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "setdefault")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _IDENT_KEY_RE.fullmatch(node.args[0].value)
                    and from_receiver(node.func.value)):
                reads.add((node.args[0].value, node.lineno))
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and from_receiver(node.value)):
                key = _sub_key(node)
                if (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                        and _IDENT_KEY_RE.fullmatch(key.value)):
                    reads.add((key.value, node.lineno))
            elif isinstance(node, ast.JoinedStr):
                sk = _skeleton(node)
                if sk and "{" in sk and _TAG_RE.fullmatch(sk):
                    tags.add((sk, node.lineno))
        return reads, tags


# ---------------------------------------------------------------------------
# crash-consistency
# ---------------------------------------------------------------------------

#: modules whose writes land on durable, cross-process paths: control
#: dirs, reshard staging, trace/heartbeat files, the native lib cache,
#: bench artifacts.  (Checkpointing itself is Orbax's atomicity.)
_DURABLE_MODULES = (
    "kubedl_tpu/journal/wal.py",
    "kubedl_tpu/journal/history.py",
    "kubedl_tpu/core/leader.py",
    "kubedl_tpu/transport/control.py",
    "kubedl_tpu/transport/blocks.py",
    "kubedl_tpu/executor/local.py",
    "kubedl_tpu/obs/trace.py",
    "kubedl_tpu/obs/steps.py",
    "kubedl_tpu/train/reshard_runtime.py",
    "kubedl_tpu/parallel/pipeline_mpmd.py",
    "kubedl_tpu/analysis/witness.py",
    "kubedl_tpu/native/build.py",
    "kubedl_tpu/codesync/git_sync.py",
    "bench.py",
)


class CrashConsistencyPass(AnalysisPass):
    """Durable writes must be crash-atomic.  In the durable modules,
    every write-mode ``open()`` must be one of: a ``.tmp``-suffixed
    path later ``os.replace``d (the blessed rename discipline), inside
    a ``*atomic*`` helper, append-mode (the JSONL logs — a torn tail
    line is skipped by readers), an ``os.fdopen`` over ``mkstemp``, or
    the bare ``open(p, "w").close()`` truncate idiom (one syscall,
    empty file is a valid state).  And within a function, a publish
    whose destination names a manifest/marker must be the LAST publish
    — the manifest is the commit point; payloads land first
    (reshard_runtime.stage_shards / write_manifest ordering)."""

    id = "crash-consistency"
    description = ("durable writes must be tmp+os.replace atomic and "
                   "publish manifests after payloads")

    def run(self, files: List[SourceFile], ctx: RepoContext) -> List[Finding]:
        by_path = {src.path: src for src in files}
        out: List[Finding] = []
        for path in _DURABLE_MODULES:
            src = by_path.get(path)
            if src is None:
                out.append(Finding(
                    self.id, path, 0,
                    f"durable module {path} not found — update "
                    f"_DURABLE_MODULES in analysis/contracts.py"))
                continue
            out.extend(self._check_file(src))
        return out

    def _check_file(self, src: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        truncates: Set[int] = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "close"
                    and isinstance(node.func.value, ast.Call)):
                truncates.add(id(node.func.value))
        for fn in self._scopes(src.tree):
            out.extend(self._check_scope(src, fn, truncates))
        return out

    @staticmethod
    def _scopes(tree: ast.AST) -> List[ast.AST]:
        return [tree] + [n for n in ast.walk(tree)
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]

    @staticmethod
    def _own_nodes(scope: ast.AST) -> List[ast.AST]:
        """Walk `scope` without descending into nested functions (each
        function is its own atomicity scope)."""
        own: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            own.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return own

    def _check_scope(
        self, src: SourceFile, scope: ast.AST, truncates: Set[int],
    ) -> List[Finding]:
        own = self._own_nodes(scope)
        name = getattr(scope, "name", "<module>")
        seg = src.segment(scope) if name != "<module>" else ""
        has_replace = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "replace"
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "os"
            for n in own)
        out: List[Finding] = []
        publishes: List[Tuple[int, str]] = []  # (line, dest segment)
        for n in own:
            if not isinstance(n, ast.Call):
                continue
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "replace"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "os" and len(n.args) == 2):
                publishes.append((n.lineno, src.segment(n.args[1])))
                continue
            callee = ""
            if isinstance(n.func, ast.Name):
                callee = n.func.id
            elif isinstance(n.func, ast.Attribute):
                callee = n.func.attr
            if "atomic" in callee and n.args:
                publishes.append((n.lineno, src.segment(n.args[0])))
                continue
            if callee not in ("open", "fdopen"):
                continue
            mode = self._mode(n)
            if mode is None or mode.startswith("r") or "a" in mode:
                continue
            if id(n) in truncates:
                continue  # open(p, "w").close() zero-byte truncate
            if "atomic" in name:
                continue
            if callee == "fdopen" and "mkstemp" in seg:
                continue  # tempfile.mkstemp + fdopen: private until linked
            path_seg = src.segment(n.args[0]) if n.args else ""
            if "tmp" in path_seg.lower() and has_replace:
                continue
            out.append(Finding(
                self.id, src.path, n.lineno,
                f"non-atomic durable write in {name}(): "
                f"open({path_seg or '...'}, {mode!r}) — write a .tmp "
                f"sibling and os.replace() it over the destination"))
        publishes.sort()
        for i, (line, dest) in enumerate(publishes):
            low = dest.lower()
            if ("manifest" in low or "marker" in low) \
                    and i < len(publishes) - 1:
                nxt = publishes[i + 1]
                out.append(Finding(
                    self.id, src.path, nxt[0],
                    f"payload published after its manifest: {name}() "
                    f"publishes {dest} (line {line}, the commit point) "
                    f"before {nxt[1]} — reorder so the manifest lands "
                    f"LAST"))
        return out

    @staticmethod
    def _mode(call: ast.Call) -> Optional[str]:
        mode: Optional[ast.AST] = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return "r"
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None
