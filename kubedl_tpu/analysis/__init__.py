"""Fleet invariant analyzer — AST lint passes + lock-order analysis.

Every review round since PR 5 has re-found the same invariant classes
drifting by hand: unescaped Prometheus label renders, metric families
missing from /debug/vars, validation rules forked between submit and
runtime, array payloads serialized outside the bf16-safe codecs, broad
``except Exception`` swallows in reconcile/consumer loops, and bench
lanes clobbering each other's committed records. This package makes the
machine enforce them (docs/static_analysis.md):

  * ``framework``  — dependency-free (stdlib ``ast``) pass registry,
    per-line/per-file allowlist pragmas that REQUIRE a justification
    string, JSON + human report;
  * ``passes``     — the repo-specific invariant passes
    (prom-escape, debug-vars-family, shared-validation, payload-dtype,
    broad-except, bench-lane-merge);
  * ``lockorder``  — static lock-acquisition-order graph over the
    concurrent planes (transport/gang/sched/serving/core): cycle
    detection + held-lock I/O findings;
  * ``witness``    — opt-in runtime lock witness (KUBEDL_LOCK_WITNESS)
    recording real acquisition orders and failing loudly on inversions.

Run it as ``make lint``, ``python -m kubedl_tpu.analysis``, or
``kubedl-tpu analyze``. The package import stays light (no jax, no
product modules) so ``witness.new_lock`` is importable from anywhere.
"""
from __future__ import annotations

from kubedl_tpu.analysis.framework import Finding, Report, run_analysis

__all__ = ["Finding", "Report", "run_analysis"]
