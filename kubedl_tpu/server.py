"""HTTP API + metrics server — the operator's network surface.

The reference serves Prometheus metrics on --metrics-addr
(ref pkg/metrics/monitor.go:27-36) and relies on the k8s API server for
object CRUD. Standalone, this server provides both:

  GET  /metrics                     Prometheus text exposition
  GET  /healthz                     liveness
  GET  /apis/<kind>                 list jobs (JSON)
  GET  /apis/<kind>/<ns>/<name>     get one job
  POST /apis/<kind>                 apply a manifest (create-or-update)
  DELETE /apis/<kind>/<ns>/<name>   delete a job
  GET  /events/<ns>                 recent events in a namespace
  GET  /trace/<ns>/<job>            flight-recorder span timeline + goodput
  GET  /history/<ns>/<job>          fleet history (outlives job TTL)
  GET  /serving/fleet               serving-fleet pods by role (JSON)
  POST /serving/drain/<ns>/<pod>    annotate a serving pod for drain

Auth: loopback binds are open; any other bind REQUIRES a bearer token
(`token=` arg or KUBEDL_API_TOKEN env) — the reference inherits
kube-apiserver authn/z, so an unauthenticated non-local surface would be
a regression. /healthz stays unauthenticated for probes.
"""
from __future__ import annotations

import hmac
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubedl_tpu.core.store import NotFound
from kubedl_tpu.utils.serde import to_dict


class OperatorHTTPServer:
    def __init__(
        self,
        operator,
        host: str = "127.0.0.1",
        port: int = 8443,
        token: Optional[str] = None,
    ) -> None:
        self.operator = operator
        self.host = host
        self.port = port
        self.token = token if token is not None else os.environ.get("KUBEDL_API_TOKEN", "")
        if not self.token and host not in ("127.0.0.1", "localhost", "::1"):
            raise ValueError(
                f"refusing to serve the operator API on {host!r} without a "
                "bearer token (set --api-token or KUBEDL_API_TOKEN)"
            )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        op = self.operator
        token = self.token

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _authorized(self) -> bool:
                if not token or self.path == "/healthz":
                    return True
                supplied = self.headers.get("Authorization", "")
                # compare bytes: str compare_digest requires ASCII and would
                # raise (not 401) on an exotic header
                if hmac.compare_digest(
                    supplied.encode("utf-8", "surrogateescape"),
                    f"Bearer {token}".encode(),
                ):
                    return True
                self._send(401, '{"error": "unauthorized"}')
                return False

            def _send(self, code: int, body: str, ctype: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code: int, obj):
                self._send(code, json.dumps(obj, indent=1))

            def do_GET(self):
                if not self._authorized():
                    return
                from urllib.parse import parse_qs, urlsplit

                split = urlsplit(self.path)
                query = parse_qs(split.query)
                parts = [p for p in split.path.split("/") if p]
                if split.path == "/metrics":
                    body = op.metrics_registry.render()
                    rm = getattr(op, "runtime_metrics", None)
                    if rm is not None:
                        body += rm.render()
                    self._send(200, body, "text/plain; version=0.0.4")
                elif split.path == "/debug/vars":
                    rm = getattr(op, "runtime_metrics", None)
                    self._json(200, rm.debug_vars() if rm is not None else {})
                elif split.path == "/healthz":
                    self._send(200, "ok", "text/plain")
                elif len(parts) == 3 and parts[0] == "logs":
                    # kubectl-logs equivalent: /logs/<ns>/<pod>[?container=&tail=]
                    ex = getattr(op, "executor", None)
                    if ex is None:
                        self._json(404, {"error": "no local executor (kube mode: "
                                                  "use kubectl logs)"})
                    else:
                        container = query.get("container", [None])[0]
                        tail_q = query.get("tail", [None])[0]
                        try:
                            tail = int(tail_q) if tail_q is not None else None
                        except ValueError:
                            self._json(400, {"error": f"bad tail {tail_q!r}"})
                            return
                        text = ex.read_logs(parts[1], parts[2],
                                            container=container, tail=tail)
                        if not text:
                            # distinguish "empty log" from a typo'd name:
                            # 404 unless the pod exists (live, or left its
                            # log dir behind after deletion)
                            try:
                                op.store.get("Pod", parts[1], parts[2])
                            except NotFound:
                                if not os.path.isdir(
                                    ex._pod_log_dir(parts[1], parts[2])
                                ):
                                    self._json(404, {
                                        "error": f"pod {parts[1]}/{parts[2]} "
                                                 f"not found"
                                    })
                                    return
                        self._send(200, text, "text/plain")
                elif len(parts) >= 2 and parts[0] == "apis":
                    kind = op._kind_by_lower.get(parts[1].lower(), parts[1])
                    if len(parts) == 2:
                        objs = op.store.list(kind)
                        self._json(200, {"kind": f"{kind}List",
                                         "items": [to_dict(o) for o in objs]})
                    elif len(parts) == 4:
                        try:
                            self._json(200, to_dict(op.store.get(kind, parts[2], parts[3])))
                        except NotFound as e:
                            self._json(404, {"error": str(e)})
                    else:
                        self._json(400, {"error": "use /apis/<kind>[/<ns>/<name>]"})
                elif len(parts) == 2 and parts[0] == "events":
                    evs = op.store.list("Event", namespace=parts[1])
                    self._json(200, {"items": [to_dict(e) for e in evs]})
                elif len(parts) == 3 and parts[0] == "trace":
                    # flight recorder (docs/observability.md): the merged
                    # cross-plane span timeline of one job + its goodput
                    # breakdown, computed from the SAME spans — what
                    # `kubedl-tpu trace <job>` renders
                    from kubedl_tpu.obs import (
                        goodput as compute_goodput,
                        job_trace_dir,
                        load_spans,
                        trace_id_for,
                    )

                    root = getattr(op, "trace_root", "")
                    d = (job_trace_dir(root, parts[1], parts[2])
                         if root else "")
                    if not d or not os.path.isdir(d):
                        self._json(404, {
                            "error": f"no trace recorded for "
                                     f"{parts[1]}/{parts[2]}"})
                        return
                    spans = load_spans(d)
                    self._json(200, {
                        "namespace": parts[1],
                        "job": parts[2],
                        "trace_id": trace_id_for(parts[1], parts[2]),
                        "spans": spans,
                        "goodput": compute_goodput(spans),
                    })
                elif len(parts) == 3 and parts[0] == "history":
                    # fleet history (docs/ha.md): everything the history
                    # store kept about one job — trace snapshot, goodput,
                    # lifecycle markers, persisted job row + events —
                    # still answerable after the CRD hit its TTL and the
                    # trace dir was garbage-collected
                    hs = getattr(op, "history_store", None)
                    if hs is None:
                        self._json(404, {
                            "error": "history store not enabled "
                                     "(set history_dir / --history-dir)"})
                        return
                    rec = hs.get(parts[1], parts[2])
                    if rec is None:
                        self._json(404, {
                            "error": f"no history recorded for "
                                     f"{parts[1]}/{parts[2]}"})
                        return
                    self._json(200, rec)
                elif split.path == "/serving/fleet":
                    # the serving-fleet view the router and operators
                    # watch: every pod carrying a serving role label,
                    # grouped by job, with phase + drain state — derived
                    # entirely from the store so it needs no extra
                    # operator wiring and stays correct across restarts
                    from kubedl_tpu.api.common import (
                        ANNOTATION_SERVING_DRAIN,
                        LABEL_JOB_NAME,
                        LABEL_SERVING_ROLE,
                    )

                    fleets: dict = {}
                    for pod in op.store.list("Pod"):
                        role = (pod.metadata.labels or {}).get(
                            LABEL_SERVING_ROLE)
                        if not role:
                            continue
                        job = (pod.metadata.labels or {}).get(
                            LABEL_JOB_NAME, "")
                        key = f"{pod.metadata.namespace}/{job}"
                        entry = fleets.setdefault(
                            key, {"prefill": [], "decode": []})
                        phase = getattr(pod.status, "phase", "")
                        entry.setdefault(role, []).append({
                            "name": pod.metadata.name,
                            "namespace": pod.metadata.namespace,
                            "phase": getattr(phase, "value",
                                             str(phase) if phase else ""),
                            "draining": ANNOTATION_SERVING_DRAIN in (
                                pod.metadata.annotations or {}),
                        })
                    self._json(200, {"fleets": fleets})
                elif split.path == "/serving/versions":
                    # weight-rollout progress per job: the published
                    # version at the tree root and each pod's committed
                    # model_version (docs/weights.md) — read straight
                    # from the weights metrics plane, so it covers every
                    # consumer riding the distribution tree
                    from kubedl_tpu.weights.metrics import weights_metrics

                    jobs = {}
                    snap = weights_metrics.snapshot()["jobs"]
                    for job, rec in snap.items():
                        jobs[job] = {
                            "published_version": rec["published_version"],
                            "pods": dict(rec["pods"]),
                            "pending": sorted(
                                p for p, v in rec["pods"].items()
                                if v < rec["published_version"]),
                        }
                    self._json(200, {"jobs": jobs})
                else:
                    self._json(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):
                if not self._authorized():
                    return
                parts = [p for p in self.path.split("/") if p]
                if (len(parts) == 4 and parts[0] == "serving"
                        and parts[1] == "drain"):
                    # kubectl-drain for a serving pod: annotate it; the
                    # pod's router loop migrates its streams and the
                    # operator can then delete it without dropping any
                    from kubedl_tpu.api.common import (
                        ANNOTATION_SERVING_DRAIN,
                        LABEL_SERVING_ROLE,
                    )

                    try:
                        pod = op.store.get("Pod", parts[2], parts[3])
                    except NotFound as e:
                        self._json(404, {"error": str(e)})
                        return
                    if LABEL_SERVING_ROLE not in (pod.metadata.labels or {}):
                        self._json(400, {
                            "error": f"pod {parts[2]}/{parts[3]} has no "
                                     f"serving role — not a fleet pod"})
                        return
                    if pod.metadata.annotations is None:
                        pod.metadata.annotations = {}
                    pod.metadata.annotations[ANNOTATION_SERVING_DRAIN] = (
                        str(int(time.time())))
                    op.store.update(pod)
                    self._json(200, {"draining": f"{parts[2]}/{parts[3]}"})
                elif len(parts) == 2 and parts[0] == "apis":
                    length = int(self.headers.get("Content-Length", "0"))
                    try:
                        manifest = json.loads(self.rfile.read(length) or b"{}")
                        manifest.setdefault("kind", parts[1])
                        job = op.apply(manifest)
                        self._json(200, to_dict(job))
                    except (ValueError, KeyError) as e:
                        self._json(400, {"error": str(e)})
                else:
                    self._json(404, {"error": f"unknown path {self.path}"})

            def do_DELETE(self):
                if not self._authorized():
                    return
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 4 and parts[0] == "apis":
                    kind = op._kind_by_lower.get(parts[1].lower(), parts[1])
                    try:
                        op.store.delete(kind, parts[2], parts[3])
                        self._json(200, {"deleted": f"{parts[2]}/{parts[3]}"})
                    except NotFound as e:
                        self._json(404, {"error": str(e)})
                else:
                    self._json(404, {"error": f"unknown path {self.path}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)
