"""Object metadata — the apimachinery subset the framework needs.

Ref: k8s.io/apimachinery ObjectMeta/OwnerReference as used throughout
/root/reference (e.g. pkg/job_controller/job_controller.go:114-126
GenOwnerReference). Timestamps are float epoch seconds internally and
RFC3339 on the wire.
"""
from __future__ import annotations

import dataclasses
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def now() -> float:
    return time.time()


def rfc3339(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def new_uid() -> str:
    return str(uuid.uuid4())


# The apiserver-owned finalizer a Foreground delete installs: the object
# stays (deletionTimestamp set) until the GC has removed every dependent
# with blockOwnerDeletion, then the finalizer is stripped and the object
# goes away (k8s metav1.FinalizerDeleteDependents).
FOREGROUND_FINALIZER = "foregroundDeletion"
DELETE_BACKGROUND = "Background"
DELETE_FOREGROUND = "Foreground"
DELETE_ORPHAN = "Orphan"
PROPAGATION_POLICIES = (DELETE_BACKGROUND, DELETE_FOREGROUND, DELETE_ORPHAN)


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    # increments only when desired state (spec) changes — status writes
    # and label/annotation churn leave it alone, so controllers can cheaply
    # detect "spec changed since I last looked" (k8s ObjectMeta.Generation)
    generation: int = 0
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    # while non-empty, a delete only MARKS the object (deletionTimestamp)
    # — it is removed when the last finalizer is stripped by whoever
    # registered it (k8s ObjectMeta.Finalizers)
    finalizers: List[str] = field(default_factory=list)

    def controller_ref(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


def namespaced_name(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"
