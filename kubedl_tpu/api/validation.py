"""Admission-style spec validation — the webhook the reference never wrote.

The reference ships webhook/certmanager kustomize scaffolding but zero
webhook Go code (SURVEY.md §2.3 Deploy/config row); invalid specs surface
as reconcile-time errors. Here validation runs at apply time (Operator.apply
and `kubedl-tpu validate`), the moral equivalent of a validating admission
webhook: reject early with field-path messages instead of failing mid-
reconcile. Workload controllers add their own rules via the
`validate_job(job)` hook (e.g. PyTorch requires a Master replica — ref
controllers/pytorch/status.go:63-91 errors there instead).
"""
from __future__ import annotations

from typing import List

from kubedl_tpu.api.common import CleanPodPolicy, RestartPolicy


class ValidationError(ValueError):
    def __init__(self, kind: str, name: str, errors: List[str]) -> None:
        self.errors = list(errors)
        super().__init__(
            f"{kind} {name!r} is invalid: " + "; ".join(self.errors)
        )


def validate_common(job, controller) -> List[str]:
    """Rules every workload shares; returns field-path error strings."""
    errs: List[str] = []
    if not job.metadata.name:
        errs.append("metadata.name: required")
    specs = controller.replica_specs(job)
    if not specs:
        errs.append("spec.replicaSpecs: at least one replica type required")
    for rtype, spec in (specs or {}).items():
        path = f"spec.replicaSpecs[{rtype}]"
        if spec.replicas is not None and spec.replicas < 0:
            errs.append(f"{path}.replicas: must be >= 0, got {spec.replicas}")
        containers = spec.template.spec.containers
        if not containers:
            errs.append(f"{path}.template.spec.containers: required")
        seen = set()
        for i, c in enumerate(containers):
            if not c.name:
                errs.append(f"{path}.template.spec.containers[{i}].name: required")
            elif c.name in seen:
                errs.append(
                    f"{path}.template.spec.containers[{i}].name: duplicate {c.name!r}"
                )
            seen.add(c.name)
        if spec.restart_policy is not None and not isinstance(
            spec.restart_policy, RestartPolicy
        ):
            errs.append(f"{path}.restartPolicy: unknown {spec.restart_policy!r}")
    rp = controller.run_policy(job)
    if rp is not None:
        if rp.clean_pod_policy is not None and not isinstance(
            rp.clean_pod_policy, CleanPodPolicy
        ):
            errs.append(f"spec.runPolicy.cleanPodPolicy: unknown {rp.clean_pod_policy!r}")
        for fname, v in (
            ("ttlSecondsAfterFinished", rp.ttl_seconds_after_finished),
            ("activeDeadlineSeconds", rp.active_deadline_seconds),
            ("backoffLimit", rp.backoff_limit),
        ):
            if v is not None and v < 0:
                errs.append(f"spec.runPolicy.{fname}: must be >= 0, got {v}")
        sp = rp.success_policy
        if sp is not None and sp.min_finish_worker_percentage is not None and not (
            0 <= sp.min_finish_worker_percentage <= 100
        ):
            errs.append(
                "spec.runPolicy.successPolicy.minFinishWorkRate: must be in "
                f"[0, 100], got {sp.min_finish_worker_percentage}"
            )
        sched = rp.scheduling_policy
        if sched is not None and sched.tpu_slice_fallbacks:
            errs.extend(_validate_elastic_shapes(sched, controller))
    return errs


def _validate_elastic_shapes(sched, controller) -> List[str]:
    """schedulingPolicy.tpuSliceFallbacks is on the SHARED policy type,
    but elastic resize restarts the job through checkpoint-restore — a
    workload must opt in (`supports_elastic`, JAXJob today) or the
    capacity scheduler would silently lose its training progress on
    every resize. Shape sanity is checked here for every kind so the
    admitter never records a fallback larger than the preferred shape."""
    from kubedl_tpu.executor.tpu_topology import parse_slice_type

    path = "spec.runPolicy.schedulingPolicy.tpuSliceFallbacks"
    errs: List[str] = []
    if not getattr(controller, "supports_elastic", False):
        return [
            f"{path}: elastic resize is not supported by "
            f"{controller.kind} (the workload must restore "
            f"shape-agnostically from checkpoint)"
        ]
    if not sched.tpu_slice:
        errs.append(f"{path}: requires tpuSlice (the preferred shape)")
        preferred = None
    else:
        try:
            preferred = parse_slice_type(sched.tpu_slice)
        except ValueError as e:
            preferred = None
            errs.append(f"spec.runPolicy.schedulingPolicy.tpuSlice: {e}")
    for alt in sched.tpu_slice_fallbacks:
        try:
            st = parse_slice_type(alt)
        except ValueError as e:
            errs.append(f"{path}: {e}")
            continue
        if preferred is not None and st.chips > preferred.chips:
            errs.append(
                f"{path}: entry {alt!r} ({st.chips} chips) exceeds the "
                f"preferred tpuSlice {sched.tpu_slice!r} "
                f"({preferred.chips} chips)"
            )
    return errs


def validate(job, controller) -> None:
    """Raise ValidationError if the (already defaulted) job is invalid."""
    errs = validate_common(job, controller)
    extra = getattr(controller, "validate_job", None)
    if extra is not None:
        errs.extend(extra(job) or [])
    if errs:
        raise ValidationError(job.kind, job.metadata.name, errs)
