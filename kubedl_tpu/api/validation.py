"""Admission-style spec validation — the webhook the reference never wrote.

The reference ships webhook/certmanager kustomize scaffolding but zero
webhook Go code (SURVEY.md §2.3 Deploy/config row); invalid specs surface
as reconcile-time errors. Here validation runs at apply time (Operator.apply
and `kubedl-tpu validate`), the moral equivalent of a validating admission
webhook: reject early with field-path messages instead of failing mid-
reconcile. Workload controllers add their own rules via the
`validate_job(job)` hook (e.g. PyTorch requires a Master replica — ref
controllers/pytorch/status.go:63-91 errors there instead).
"""
from __future__ import annotations

from typing import List

from kubedl_tpu.api.common import CleanPodPolicy, RestartPolicy


class ValidationError(ValueError):
    def __init__(self, kind: str, name: str, errors: List[str]) -> None:
        self.errors = list(errors)
        super().__init__(
            f"{kind} {name!r} is invalid: " + "; ".join(self.errors)
        )


def validate_common(job, controller) -> List[str]:
    """Rules every workload shares; returns field-path error strings."""
    errs: List[str] = []
    if not job.metadata.name:
        errs.append("metadata.name: required")
    specs = controller.replica_specs(job)
    if not specs:
        errs.append("spec.replicaSpecs: at least one replica type required")
    for rtype, spec in (specs or {}).items():
        path = f"spec.replicaSpecs[{rtype}]"
        if spec.replicas is not None and spec.replicas < 0:
            errs.append(f"{path}.replicas: must be >= 0, got {spec.replicas}")
        containers = spec.template.spec.containers
        if not containers:
            errs.append(f"{path}.template.spec.containers: required")
        seen = set()
        for i, c in enumerate(containers):
            if not c.name:
                errs.append(f"{path}.template.spec.containers[{i}].name: required")
            elif c.name in seen:
                errs.append(
                    f"{path}.template.spec.containers[{i}].name: duplicate {c.name!r}"
                )
            seen.add(c.name)
        if spec.restart_policy is not None and not isinstance(
            spec.restart_policy, RestartPolicy
        ):
            errs.append(f"{path}.restartPolicy: unknown {spec.restart_policy!r}")
    rp = controller.run_policy(job)
    if rp is not None:
        if rp.clean_pod_policy is not None and not isinstance(
            rp.clean_pod_policy, CleanPodPolicy
        ):
            errs.append(f"spec.runPolicy.cleanPodPolicy: unknown {rp.clean_pod_policy!r}")
        for fname, v in (
            ("ttlSecondsAfterFinished", rp.ttl_seconds_after_finished),
            ("activeDeadlineSeconds", rp.active_deadline_seconds),
            ("backoffLimit", rp.backoff_limit),
        ):
            if v is not None and v < 0:
                errs.append(f"spec.runPolicy.{fname}: must be >= 0, got {v}")
        sp = rp.success_policy
        if sp is not None and sp.min_finish_worker_percentage is not None and not (
            0 <= sp.min_finish_worker_percentage <= 100
        ):
            errs.append(
                "spec.runPolicy.successPolicy.minFinishWorkRate: must be in "
                f"[0, 100], got {sp.min_finish_worker_percentage}"
            )
        sched = rp.scheduling_policy
        if sched is not None and sched.tpu_slice_fallbacks:
            errs.extend(_validate_elastic_shapes(sched, controller))
    return errs


def _validate_elastic_shapes(sched, controller) -> List[str]:
    """schedulingPolicy.tpuSliceFallbacks is on the SHARED policy type,
    but elastic resize restarts the job through checkpoint-restore — a
    workload must opt in (`supports_elastic`, JAXJob today) or the
    capacity scheduler would silently lose its training progress on
    every resize. Shape sanity is checked here for every kind so the
    admitter never records a fallback larger than the preferred shape."""
    from kubedl_tpu.executor.tpu_topology import parse_slice_type

    path = "spec.runPolicy.schedulingPolicy.tpuSliceFallbacks"
    errs: List[str] = []
    if not getattr(controller, "supports_elastic", False):
        return [
            f"{path}: elastic resize is not supported by "
            f"{controller.kind} (the workload must restore "
            f"shape-agnostically from checkpoint)"
        ]
    if not sched.tpu_slice:
        errs.append(f"{path}: requires tpuSlice (the preferred shape)")
        preferred = None
    else:
        try:
            preferred = parse_slice_type(sched.tpu_slice)
        except ValueError as e:
            preferred = None
            errs.append(f"spec.runPolicy.schedulingPolicy.tpuSlice: {e}")
    for alt in sched.tpu_slice_fallbacks:
        try:
            st = parse_slice_type(alt)
        except ValueError as e:
            errs.append(f"{path}: {e}")
            continue
        if preferred is not None and st.chips > preferred.chips:
            errs.append(
                f"{path}: entry {alt!r} ({st.chips} chips) exceeds the "
                f"preferred tpuSlice {sched.tpu_slice!r} "
                f"({preferred.chips} chips)"
            )
    return errs


PIPELINE_SCHEDULES = ("gpipe", "1f1b")


def validate_pipeline_shapes(
    n_stages: int,
    n_microbatches: int,
    interleave: int = 1,
    n_layers: int = None,
    schedule: str = None,
    path: str = "spec.pipeline",
) -> List[str]:
    """Pipeline shape sanity — the ONE rule set shared by JAXJob submit
    validation (workloads/jaxjob.py) and the runtime schedule builders
    (parallel/pipeline.py, parallel/pipeline_mpmd.py), same no-drift
    discipline as spec.serving: a shape the trainer would reject minutes
    into a job must already be rejected at apply time. Pure arithmetic —
    no jax import, so the operator path stays lean. `n_layers=None`
    skips the divisibility rule (unknown at submit unless declared);
    `schedule=None` skips the schedule-name/interleave pairing rules
    (callers that already resolved a schedule pass it so a future
    schedule added in one place cannot drift past the other)."""
    errs: List[str] = []
    if schedule is not None:
        if schedule not in PIPELINE_SCHEDULES:
            errs.append(
                f"{path}.schedule: unknown {schedule!r} "
                f"({', '.join(PIPELINE_SCHEDULES)})")
        elif interleave > 1 and schedule != "1f1b":
            errs.append(
                f"{path}.interleave > 1 requires schedule '1f1b' "
                f"(GPipe has no virtual stages)")
    if n_stages < 1:
        errs.append(f"{path}.stages: must be >= 1, got {n_stages}")
    if interleave < 1:
        errs.append(f"{path}.interleave: must be >= 1, got {interleave}")
    if n_stages >= 1 and n_microbatches < n_stages:
        # fewer microbatches than stages can never fill the pipeline —
        # the schedule would deadlock on (or garbage-feed) empty slots
        errs.append(
            f"{path}.microbatches: need >= stages ({n_stages}) to fill "
            f"the pipeline, got {n_microbatches}")
    if (n_layers is not None and n_stages >= 1 and interleave >= 1
            and n_layers % (n_stages * interleave)):
        errs.append(
            f"{path}: layer count {n_layers} not divisible by stages x "
            f"interleave = {n_stages} x {interleave} (every rank must "
            f"hold {interleave} equal layer chunks)")
    return errs


RL_REWARDS = ("token-match", "length")
RL_ROLLOUT_ENGINES = ("decode", "serving")


def validate_rl_shapes(
    actor_replicas: int,
    learner_replicas: int,
    group_size: int,
    max_weight_lag: int,
    prompts_per_step: int = 1,
    max_new_tokens: int = 1,
    temperature: float = 1.0,
    broadcast_interval: int = 1,
    reward: str = "token-match",
    eos_id: int = -1,
    rollout_engine: str = "decode",
    path: str = "spec.rl",
) -> List[str]:
    """RL-fleet shape sanity — the ONE rule set shared by JAXJob submit
    validation (workloads/jaxjob.py) and the pod runtimes
    (train/rl_pod.py), the validate_pipeline_shapes discipline: a fleet
    the learner would reject minutes in must already be rejected at
    apply time. Pure arithmetic, no jax import."""
    errs: List[str] = []
    if actor_replicas < 1:
        errs.append(f"{path}.actorReplicas: must be >= 1, got "
                    f"{actor_replicas}")
    if learner_replicas != 1:
        # the sharded GRPO step is ONE program; a learner data-parallel
        # group would need cross-learner gradient sync the plane does
        # not carry yet — refuse rather than silently train n diverging
        # policies
        errs.append(f"{path}.learnerReplicas: must be exactly 1, got "
                    f"{learner_replicas}")
    if group_size < 2:
        errs.append(f"{path}.groupSize: must be >= 2 (the group mean is "
                    f"the GRPO baseline; one sample always has advantage "
                    f"0), got {group_size}")
    if max_weight_lag < 0:
        errs.append(f"{path}.maxWeightLag: must be >= 0, got "
                    f"{max_weight_lag}")
    if prompts_per_step < 1:
        errs.append(f"{path}.promptsPerStep: must be >= 1, got "
                    f"{prompts_per_step}")
    if max_new_tokens < 1:
        errs.append(f"{path}.maxNewTokens: must be >= 1, got "
                    f"{max_new_tokens}")
    if temperature <= 0:
        errs.append(f"{path}.temperature: must be > 0 (greedy rollouts "
                    f"make all G samples of a group identical, zeroing "
                    f"every advantage), got {temperature}")
    if broadcast_interval < 1:
        errs.append(f"{path}.broadcastInterval: must be >= 1, got "
                    f"{broadcast_interval}")
    elif (actor_replicas >= 1 and max_weight_lag >= 0
            and broadcast_interval > actor_replicas * (max_weight_lag + 1)):
        # the learner needs broadcastInterval updates' worth of
        # trajectories to reach the NEXT version, but the actors' parking
        # guard stops the fleet at actorReplicas * (maxWeightLag + 1)
        # generations per version — past that the whole fleet deadlocks
        # (actors parked for a version the learner can never reach),
        # times out, restarts, and deadlocks again forever
        errs.append(
            f"{path}.broadcastInterval: {broadcast_interval} exceeds "
            f"actorReplicas * (maxWeightLag + 1) = "
            f"{actor_replicas * (max_weight_lag + 1)} — the actors park "
            f"after that many generations per weight version, so the "
            f"learner could never collect enough trajectories to publish "
            f"the next one (the fleet would deadlock)")
    if reward not in RL_REWARDS and ":" not in reward:
        errs.append(f"{path}.reward: unknown {reward!r} "
                    f"({', '.join(RL_REWARDS)}, or 'module.path:fn')")
    if reward == "length" and eos_id < 0:
        errs.append(f"{path}.reward 'length' needs {path}.eosId >= 0: "
                    f"without a stop token every completion is exactly "
                    f"maxNewTokens long and every group's reward is "
                    f"constant — training would be a no-op")
    if rollout_engine not in RL_ROLLOUT_ENGINES:
        errs.append(f"{path}.rolloutEngine: unknown {rollout_engine!r} "
                    f"({', '.join(RL_ROLLOUT_ENGINES)})")
    return errs


def validate(job, controller) -> None:
    """Raise ValidationError if the (already defaulted) job is invalid."""
    errs = validate_common(job, controller)
    extra = getattr(controller, "validate_job", None)
    if extra is not None:
        errs.extend(extra(job) or [])
    if errs:
        raise ValidationError(job.kind, job.metadata.name, errs)
