"""Common job API ("common v1") — the shared vocabulary for every workload.

Re-derives the reference's pkg/job_controller/api/v1/types.go:23-191
(JobStatus/ReplicaSpec/RunPolicy/conditions) and the condition machine of
pkg/util/status.go:50-137, whose invariants are behavioral API:
  * Failed is sticky — once JobFailed is set no condition may change,
  * Running and Restarting are mutually exclusive,
  * Running flips to False (not removed) when a terminal condition lands.

TPU-native extensions over the reference:
  * RunPolicy.success_policy promotes XDL's min-finish-workers semantics
    (ref api/xdl/v1alpha1/types.go:38-49) to the common layer,
  * SchedulingPolicy gains TPU slice topology fields so gang admission can be
    all-or-nothing per slice (ref SchedulingPolicy.MinAvailable at
    types.go:189-191 existed but was never plumbed — we plumb it).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubedl_tpu.api.meta import now
from kubedl_tpu.api.pod import PodPhase, PodTemplateSpec

# ---------------------------------------------------------------------------
# Labels / annotations (ref pkg/job_controller/api/v1/constants.go:3-33)
# ---------------------------------------------------------------------------

LABEL_REPLICA_INDEX = "replica-index"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_JOB_ROLE = "job-role"

# Multislice: which TPU slice of a multi-slice gang a pod belongs to
# (workloads/jaxjob.py stamps it; the slice admitter places by it).
LABEL_SLICE_ID = "kubedl-tpu.io/slice-id"

# Serving fleet: a pod's role in a disaggregated serving JAXJob
# ("prefill" | "decode"); workloads/jaxjob.py stamps it, server.py's
# /serving/fleet endpoint groups by it, and the router drains by it.
LABEL_SERVING_ROLE = "kubedl-tpu.io/serving-role"
# RL fleet: a pod's role in an actor/learner JAXJob ("actor" |
# "learner"); workloads/jaxjob.py stamps it by worker index (actors
# first), matching the mixed-role gang's slice order.
LABEL_RL_ROLE = "kubedl-tpu.io/rl-role"
# Drain request: the operator (POST /serving/drain) annotates the pod;
# the pod's router loop notices and migrates its streams.
ANNOTATION_SERVING_DRAIN = "kubedl-tpu.io/serving-drain"


def slice_group(total: int, num_slices: int, index: int):
    """THE multislice grouping convention, in one place: `total` workers
    divide into `num_slices` contiguous index groups. Returns
    (slice_id, in_slice_index, per_slice). Everything that reasons about
    slice membership — env injection (workloads/jaxjob.py), GKE worker
    identity (k8s/gke.py), gang placement (gang/slice_admitter.py) — must
    go through this so the three can never drift apart.

    Degenerate inputs (num_slices < 2, or total not divisible) collapse to
    single-slice semantics: everything in slice 0, index unchanged.
    """
    num_slices = int(num_slices or 1)
    total = int(total or 0)
    if num_slices < 2 or total <= 0 or total % num_slices:
        return 0, index, max(total, 1)
    per_slice = total // num_slices
    return index // per_slice, index % per_slice, per_slice

ANNOTATION_GIT_SYNC_CONFIG = "kubedl.io/git-sync-config"
ANNOTATION_TENANCY = "kubedl.io/tenancy"

JOB_ROLE_MASTER = "master"

GROUP_NAME = "kubedl-tpu.io"

# ---------------------------------------------------------------------------
# Enums
# ---------------------------------------------------------------------------


class ReplicaType(str, enum.Enum):
    # The union of replica types across workloads; each workload declares the
    # subset it supports (ref: per-workload types.go files).
    MASTER = "Master"
    WORKER = "Worker"
    CHIEF = "Chief"
    PS = "PS"
    EVALUATOR = "Evaluator"
    SCHEDULER = "Scheduler"
    EXTEND_ROLE = "ExtendRole"
    COORDINATOR = "Coordinator"  # JAXJob (net-new)


class JobConditionType(str, enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class ConditionStatus(str, enum.Enum):
    TRUE = "True"
    FALSE = "False"
    UNKNOWN = "Unknown"


class CleanPodPolicy(str, enum.Enum):
    UNDEFINED = ""
    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class RestartPolicy(str, enum.Enum):
    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    # ExitCode: 1-127 permanent, retryable set per utils/exit_codes.py
    # (ref pkg/job_controller/api/v1/types.go:150-156).
    EXIT_CODE = "ExitCode"


# Condition reasons (ref pkg/util/status.go:10-19).
REASON_JOB_CREATED = "JobCreated"
REASON_JOB_RUNNING = "JobRunning"
REASON_JOB_RESTARTING = "JobRestarting"
REASON_JOB_SUCCEEDED = "JobSucceeded"
REASON_JOB_FAILED = "JobFailed"


# ---------------------------------------------------------------------------
# Spec types
# ---------------------------------------------------------------------------


@dataclass
class ReplicaSpec:
    """Ref pkg/job_controller/api/v1/types.go:65-79."""

    replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: Optional[RestartPolicy] = None


@dataclass
class SuccessPolicy:
    """XDL's min-finish success policy promoted to the common layer.

    Ref api/xdl/v1alpha1/types.go:38-49 + controllers/xdl/status.go
    calculateMinFinish: percentage takes precedence over the absolute
    number when both are set; percentage uses ceil. We additionally clamp
    the absolute number to the worker count (the reference lets an
    over-large MinFinishWorkerNum make the job unfinishable).
    """

    min_finish_worker_num: Optional[int] = None
    min_finish_worker_percentage: Optional[int] = None

    def min_finish(self, total_workers: int) -> int:
        if self.min_finish_worker_percentage is not None:
            pct = min(max(self.min_finish_worker_percentage, 0), 100)
            return -(-total_workers * pct // 100)  # ceil division
        if self.min_finish_worker_num is not None:
            return min(self.min_finish_worker_num, total_workers)
        return total_workers


@dataclass
class SchedulingPolicy:
    """Ref types.go:189-191 + TPU-native slice fields (net-new)."""

    min_available: Optional[int] = None
    # TPU slice requested for the whole gang, e.g. "v5e-8", "v5p-32".
    tpu_slice: str = ""
    # Physical topology request, e.g. "2x4" / "4x4x4".
    tpu_topology: str = ""
    # Admission priority: higher wins a freed slice; ties go FIFO by gang
    # creation (net-new — the reference delegates ordering to kube-batch).
    priority: int = 0
    # Elastic (net-new, Tenplex-style): ordered SMALLER shapes the job
    # also accepts, preferred-first after tpu_slice. The capacity
    # scheduler (sched/) may re-admit the gang at any of these under
    # contention and grow it back when capacity frees; the workload must
    # restore shape-agnostically from checkpoint (docs/scheduling.md).
    tpu_slice_fallbacks: List[str] = field(default_factory=list)


@dataclass
class RunPolicy:
    """Ref types.go:162-185."""

    clean_pod_policy: Optional[CleanPodPolicy] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    success_policy: Optional[SuccessPolicy] = None


# ---------------------------------------------------------------------------
# Status types
# ---------------------------------------------------------------------------


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class JobCondition:
    type: JobConditionType = JobConditionType.CREATED
    status: ConditionStatus = ConditionStatus.TRUE
    reason: str = ""
    message: str = ""
    last_update_time: Optional[float] = None
    last_transition_time: Optional[float] = None


@dataclass
class JobStatus:
    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None


# ---------------------------------------------------------------------------
# Condition machine (ref pkg/util/status.go:25-137)
# ---------------------------------------------------------------------------


def get_condition(status: JobStatus, ctype: JobConditionType) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def has_condition(status: JobStatus, ctype: JobConditionType) -> bool:
    c = get_condition(status, ctype)
    return c is not None and c.status == ConditionStatus.TRUE


def is_created(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.CREATED)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.RUNNING)


def is_restarting(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.RESTARTING)


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def update_job_conditions(
    status: JobStatus, ctype: JobConditionType, reason: str, message: str
) -> None:
    """Set condition `ctype` True, preserving the reference's invariants.

    Ref pkg/util/status.go:88-137 — Failed sticky; no-op when status+reason
    unchanged; transition time preserved when only reason/message change;
    Running<->Restarting mutual exclusion; Running demoted to False on
    terminal conditions.
    """
    if is_failed(status):
        return

    ts = now()
    cond = JobCondition(
        type=ctype,
        status=ConditionStatus.TRUE,
        reason=reason,
        message=message,
        last_update_time=ts,
        last_transition_time=ts,
    )
    current = get_condition(status, ctype)
    if current is not None and current.status == cond.status and current.reason == cond.reason:
        return
    if current is not None and current.status == cond.status:
        cond.last_transition_time = current.last_transition_time

    kept: List[JobCondition] = []
    for c in status.conditions:
        if ctype == JobConditionType.RESTARTING and c.type == JobConditionType.RUNNING:
            continue
        if ctype == JobConditionType.RUNNING and c.type == JobConditionType.RESTARTING:
            continue
        if c.type == ctype:
            continue
        if (
            ctype in (JobConditionType.FAILED, JobConditionType.SUCCEEDED)
            and c.type == JobConditionType.RUNNING
        ):
            c.status = ConditionStatus.FALSE
        kept.append(c)
    kept.append(cond)
    status.conditions = kept


def replica_key(rtype) -> str:
    """Canonical status-map key for a replica type.

    Replica types are open strings in the reference (custom roles like XDL's
    ExtendRole are legal), so unknown names pass through instead of raising.
    """
    if isinstance(rtype, ReplicaType):
        return rtype.value
    return str(rtype)


def initialize_replica_statuses(status: JobStatus, replica_types) -> None:
    """Reset the given types' tallies, preserving others (ref status.go:9-16)."""
    for rt in replica_types:
        status.replica_statuses[replica_key(rt)] = ReplicaStatus()


def update_job_replica_statuses(status: JobStatus, rtype, pod) -> None:
    """Tally one pod's phase into the replica status (ref status.go:18-27)."""
    rs = status.replica_statuses.setdefault(replica_key(rtype), ReplicaStatus())
    phase = pod.status.phase
    if phase == PodPhase.RUNNING:
        rs.active += 1
    elif phase == PodPhase.SUCCEEDED:
        rs.succeeded += 1
    elif phase == PodPhase.FAILED:
        rs.failed += 1
