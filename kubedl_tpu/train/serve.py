"""HTTP serving workload — the continuous-batching engine as a JAXJob.

Completes the operator's train -> checkpoint -> serve loop: a JAXJob
runs this module (examples/jax_job_serving.yaml), it restores params
from the trainer's Orbax checkpoint, and serves generation over a small
JSON API backed by `models/serving.ServingEngine`:

    POST /generate   {"tokens": [..], "max_new_tokens": 64,
                      "eos_token": 2?, "prefix_id": 0?} -> {"tokens": [...]}
                     (with an --hf-model tokenizer, {"text": "..."} works
                      too and the response adds decoded "text")
    POST /generate   {"requests": [{...}, ...]}  (batch form; each entry
                      rides its own engine slot)  -> {"results": [...]}
    POST /prefix     {"tokens": [...]}  -> {"prefix_id": N}   (shared
                      system prompts prefill once; see register_prefix)
    GET  /stats      -> ServingEngine.stats()
    GET  /metrics    -> Prometheus text format (kubedl_serving_* gauges)
    GET  /healthz    -> {"ok": true}

One background thread drives `engine.step()` whenever work is pending —
request handlers only enqueue and wait, so concurrent HTTP clients
batch onto the same decode ticks (that's the continuous-batching win).
The reference has no serving stack at all (SURVEY §2.4).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger("kubedl_tpu.serve")


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubedl-serve")
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"),
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"])
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""))
    p.add_argument("--hf-model", default=os.environ.get("KUBEDL_HF_MODEL", ""),
                   help="Hugging Face Llama name/dir — overrides --model/"
                        "--checkpoint-path (models/import_hf.py)")
    p.add_argument("--allow-fresh-init", action="store_true")
    p.add_argument("--lora-checkpoint-path", default="",
                   help="merge the newest adapter checkpoint from a trainer "
                        "--lora-rank run into the base weights")
    p.add_argument("--lora-alpha", type=float, default=None)
    p.add_argument("--adapter", action="append", default=[],
                   metavar="CKPT[:ALPHA]",
                   help="register a LoRA adapter checkpoint at startup "
                        "for per-request selection (repeatable; ids are "
                        "assigned in order starting at 1). Unlike "
                        "--lora-checkpoint-path (which MERGES one adapter "
                        "into the weights), these serve side-by-side with "
                        "the base model")
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--port", type=int, default=int(os.environ.get("PORT", 8000)))
    # operator pods get these via the spec.serving KUBEDL_SERVING_*
    # injection (workloads/jaxjob.py); flags still win when passed
    p.add_argument("--slots", type=int,
                   default=int(os.environ.get("KUBEDL_SERVING_SLOTS", 8)))
    p.add_argument("--max-len", type=int,
                   default=int(os.environ.get("KUBEDL_SERVING_MAX_LEN", 1024)))
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 (models/quant.py)")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache with exact scale folding — half the "
                        "per-token cache read at long contexts")
    p.add_argument("--draft-model", default="",
                   help="named config for a speculative draft model "
                        "(models/llama.py config_for); requires "
                        "--draft-checkpoint-path or --draft-hf-model")
    p.add_argument("--draft-checkpoint-path", default="",
                   help="Orbax checkpoint for the draft model")
    p.add_argument("--draft-hf-model", default="",
                   help="HF checkpoint for the draft model (must share "
                        "the target's tokenizer)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens proposed per speculative round")
    p.add_argument("--max-steps", type=int, default=0,
                   help="stop after N pump passes, each up to --decode-block "
                        "device ticks (smoke tests); 0 = forever")
    p.add_argument("--decode-block", type=int, default=8,
                   help="max ticks fused per host sync (serving.py "
                        "step_block): bigger amortizes dispatch/sync "
                        "overhead, smaller tightens streaming latency; "
                        "1 = tick per sync")
    return p.parse_args(argv)


class _Service:
    """Engine + queue pump shared by all HTTP handler threads."""

    def __init__(self, engine, tokenizer=None, decode_block: int = 8) -> None:
        self.engine = engine
        self.tokenizer = tokenizer
        self.decode_block = max(int(decode_block), 1)
        self._lock = threading.Lock()  # engine calls are single-threaded
        self._work = threading.Event()
        self._stop = threading.Event()
        self.ticks = 0
        self._thread = threading.Thread(
            target=self._pump, name="serve-pump", daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        while not self._stop.is_set():
            if not self._work.wait(timeout=0.1):
                continue
            with self._lock:
                if not self.engine.has_pending():
                    self._work.clear()
                    continue
                try:
                    if self.decode_block > 1:
                        self.engine.step_block(self.decode_block)
                    else:
                        self.engine.step()
                except Exception as e:  # noqa: BLE001
                    # a step that throws (bad state, OOM, device error)
                    # must not kill the pump thread silently: waiting
                    # clients would hang until their timeouts while
                    # submits keep returning 200. Fail the in-flight
                    # work loudly and keep serving.
                    print(f"serve pump: engine step failed: "
                          f"{type(e).__name__}: {e}", flush=True)
                    for req in list(self.engine._queue) + [
                            r for r in self.engine._slot_req
                            if r is not None]:
                        self.engine.cancel(req)
                # pump passes, not device ticks: the smoke-mode budget
                # just needs a monotonic progress counter
                self.ticks += 1

    def submit(self, prompt, max_new_tokens: int, eos_token: Optional[int],
               prefix_id: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: int = 0, top_p: float = 1.0,
               logprobs: bool = False, adapter_id: int = 0, stop=None):
        with self._lock:
            req = self.engine.submit(prompt, max_new_tokens, eos_token,
                                     prefix_id=prefix_id,
                                     temperature=temperature,
                                     top_k=top_k, top_p=top_p,
                                     logprobs=logprobs,
                                     adapter_id=adapter_id, stop=stop)
        self._work.set()
        return req

    def register_adapter(self, checkpoint_path: str, alpha=None) -> int:
        """Load a trainer --lora-rank adapter checkpoint and register it
        for per-request selection. The disk restore runs OUTSIDE the
        service lock (it can take seconds); only the registry swap —
        which retraces the next tick — holds it."""
        from kubedl_tpu.train.generate import restore_params

        adapters = restore_params(checkpoint_path, label="lora adapters")
        if adapters is None:
            raise ValueError(
                f"no adapter checkpoint under {checkpoint_path!r}")
        with self._lock:
            return self.engine.register_adapter(adapters, alpha=alpha)

    def register_prefix(self, tokens) -> int:
        # NOT under the service lock: the prefill compile can take tens
        # of seconds on a real chip and must not freeze the tick pump;
        # the engine's own prefix lock guards its registry
        return self.engine.register_prefix(tokens)

    def kick(self) -> None:
        """Nudge the pump (streaming handlers poll instead of wait())."""
        self._work.set()

    def wait(self, reqs, timeout: float = 300.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(r.done for r in reqs):
                return True
            self._work.set()
            time.sleep(0.005)
        return False

    def cancel(self, reqs) -> None:
        with self._lock:
            for r in reqs:
                self.engine.cancel(r)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _parse_stop(value, tok):
    """"stop" field -> list of token-id sequences. Accepts one string, a
    list of strings (tokenizer required; encoded without special
    tokens), or a list of id-lists — the OpenAI surface adapted to the
    token-id API.

    String stops are encoded ONCE and matched at token level: a
    tokenizer that merges context differently (leading-space variants)
    can produce output text containing the string without the token
    tail ever matching. Pass token-id lists for exact control."""
    if value is None:
        return None
    if isinstance(value, str):
        value = [value]
    if not isinstance(value, list):
        raise ValueError("stop must be a string or a list")
    out = []
    for s in value:
        if isinstance(s, str):
            if tok is None:
                raise ValueError("string stop sequences need a tokenizer "
                                 "— start the server with --hf-model, or "
                                 "pass token-id lists")
            out.append(tok.encode(s, add_special_tokens=False))
        elif isinstance(s, list):
            out.append([int(t) for t in s])
        else:
            raise ValueError("each stop entry must be a string or id list")
    return out


def _parse_bool(value, field: str) -> bool:
    """Strict JSON-boolean field: every other sampling param funnels bad
    input to the 422 path, so `\"logprobs\": 5` (OpenAI's top-N form,
    unsupported) or \"false\" must not silently coerce to True."""
    if value is None:
        return False
    if isinstance(value, bool):
        return value
    raise ValueError(f"{field} must be a JSON boolean, got {value!r}")


class _StreamDecoder:
    """Incremental detokenization for SSE text deltas.

    Decoding each token prefix from scratch is O(n^2) per stream AND
    wrong for multi-byte characters (a UTF-8 char split across tokens
    decodes to U+FFFD until its last byte arrives, and the 'fixed'
    decode is not a string extension of the broken one). The standard
    fix: decode over a short sliding window [prefix:read) vs
    [prefix:], emit the extension only once it no longer ends in a
    replacement char, and advance the window — O(window) per token,
    deltas concatenate exactly to the final text (modulo a held-back
    tail the final event's fresh full decode supplies)."""

    def __init__(self, tok) -> None:
        self.tok = tok
        self.toks: list = []
        self.prefix = 0  # window start
        self.read = 0    # tokens already reflected in emitted text

    def push(self, token: int) -> str:
        self.toks.append(token)
        prev = self.tok.decode(self.toks[self.prefix:self.read],
                               skip_special_tokens=True)
        full = self.tok.decode(self.toks[self.prefix:],
                               skip_special_tokens=True)
        if full.endswith("�"):
            return ""  # mid-character: hold until it completes
        if len(full) > len(prev) and full.startswith(prev):
            self.prefix = self.read
            self.read = len(self.toks)
            return full[len(prev):]
        return ""


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — quiet
        pass

    @property
    def svc(self) -> _Service:
        return self.server.svc  # type: ignore[attr-defined]

    def _send(self, status: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802
        if self.path == "/healthz":
            return self._send(200, {"ok": True})
        if self.path == "/stats":
            stats = self.svc.engine.stats()
            stats["ticks"] = self.svc.ticks
            return self._send(200, stats)
        if self.path == "/metrics":
            # Prometheus text format, matching the operator's exporter
            # conventions (docs/metrics.md) so one scrape config covers
            # operator and serving pods
            stats = self.svc.engine.stats()
            stats["ticks"] = self.svc.ticks
            lines = []
            for key, val in sorted(stats.items()):
                if not isinstance(val, (int, float)):
                    continue
                name = f"kubedl_serving_{key}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {float(val)}")
            payload = ("\n".join(lines) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        self._send(404, {"error": f"unknown path {self.path}"})

    def _stream_response(self, req, timeout: float = 300.0) -> None:
        """Server-sent events: one `data:` line per emitted token as the
        engine produces it, then a final summary event. Start the server
        with --decode-block 1 for true per-token latency (larger blocks
        emit in bursts of up to that many ticks). ANY handler exit
        before completion — disconnect, socket timeout, deadline —
        cancels the request so an abandoned stream doesn't keep its
        slot generating tokens nobody reads."""
        import time as _time

        tok = self.svc.tokenizer
        dec = _StreamDecoder(tok) if tok is not None else None
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # no Content-Length: the stream ends at EOF, so this connection
        # can't be reused — advertise that instead of chunked framing
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        sent = 0
        # stop sequences trim the token tail when they match, so (a) any
        # token still within the longest stop's reach is HELD BACK until
        # the request finishes (else the stream would leak a partial
        # match the final result excludes), and (b) that same margin
        # keeps `sent` out of the region _emit may delete, preserving
        # the unlocked reader's safety
        margin = max((len(s) for s in req.stop_sequences), default=0)
        deadline = _time.monotonic() + timeout
        try:
            while True:
                done = req.done  # read BEFORE draining: no lost-wakeup
                toks = list(req.tokens)
                lps = list(req.token_logprobs)
                limit = len(toks) if done else max(len(toks) - margin, 0)
                while sent < limit:
                    event = {"token": toks[sent], "request_id": req.request_id}
                    if req.logprobs and sent < len(lps):
                        event["logprob"] = lps[sent]
                    if dec is not None:
                        event["text_delta"] = dec.push(toks[sent])
                    self.wfile.write(
                        b"data: " + json.dumps(event).encode() + b"\n\n")
                    sent += 1
                self.wfile.flush()
                if done:
                    final = {"done": True, "tokens": toks,
                             "request_id": req.request_id}
                    if req.error:
                        # engine-side failure (e.g. poisoned prefill):
                        # done with empty tokens and the reason attached
                        final["error"] = req.error
                    if req.logprobs:
                        final["logprobs"] = list(req.token_logprobs)
                    if tok is not None:
                        # fresh full decode: deltas held back for an
                        # incomplete multi-byte char still land here
                        final["text"] = tok.decode(
                            toks, skip_special_tokens=True)
                    self.wfile.write(
                        b"data: " + json.dumps(final).encode() + b"\n\n")
                    self.wfile.flush()
                    return
                if _time.monotonic() > deadline:
                    self.wfile.write(
                        b"data: " + json.dumps(
                            {"error": "generation timed out",
                             "request_id": req.request_id}).encode() + b"\n\n")
                    self.wfile.flush()
                    return
                self.svc.kick()
                _time.sleep(0.005)
        except (BrokenPipeError, ConnectionResetError):
            pass  # the finally clause frees the slot
        finally:
            if not req.done:
                # every abnormal exit path — disconnect, ETIMEDOUT or
                # any other OSError from the socket, deadline — must
                # free the slot for live clients
                self.svc.cancel([req])

    def do_POST(self) -> None:  # noqa: N802
        if self.path not in ("/generate", "/prefix", "/adapter"):
            return self._send(404, {"error": f"unknown path {self.path}"})
        try:
            length = int(self.headers.get("Content-Length", "0") or "0")
            body = json.loads(self.rfile.read(length))
        except (json.JSONDecodeError, ValueError) as e:
            return self._send(400, {"error": f"bad JSON: {e}"})
        if not isinstance(body, dict):
            return self._send(400, {"error": "body must be a JSON object"})
        if self.path == "/prefix":
            try:
                pid = self.svc.register_prefix(body.get("tokens") or [])
            except (ValueError, TypeError) as e:
                return self._send(422, {"error": str(e)})
            return self._send(200, {"prefix_id": pid})
        if self.path == "/adapter":
            alpha = body.get("alpha")
            try:
                aid = self.svc.register_adapter(
                    str(body.get("checkpoint_path") or ""),
                    alpha=None if alpha is None else float(alpha))
            except (ValueError, TypeError) as e:
                return self._send(422, {"error": str(e)})
            return self._send(200, {"adapter_id": aid})
        try:
            stream = _parse_bool(body.get("stream"), "stream")
        except ValueError as e:
            return self._send(422, {"error": str(e)})
        entries = body.get("requests")
        single = entries is None
        if single:
            entries = [body]
        if stream and not single:
            return self._send(422, {"error": "stream only supports the "
                                             "single-request form"})
        tok = self.svc.tokenizer
        reqs = []
        try:
            for e in entries:
                if not isinstance(e, dict):
                    raise ValueError("each request must be a JSON object")
                provided = [k for k in ("tokens", "text", "messages")
                            if e.get(k) is not None]
                if len(provided) > 1:
                    raise ValueError(
                        "pass exactly one of tokens / text / messages, "
                        f"got {'+'.join(provided)}")
                tokens = e.get("tokens")
                msgs = e.get("messages")
                is_text = tokens is None and e.get("text") is not None
                if msgs is not None:
                    # chat form: the tokenizer's own template renders the
                    # conversation (plus generation prompt) into ids
                    if tok is None:
                        raise ValueError(
                            "messages need a tokenizer — start the "
                            "server with --hf-model")
                    if not (isinstance(msgs, list) and msgs and all(
                            isinstance(m, dict) and "role" in m
                            and "content" in m for m in msgs)):
                        raise ValueError(
                            "messages must be a non-empty list of "
                            "{role, content} objects")
                    try:
                        tokens = tok.apply_chat_template(
                            msgs, add_generation_prompt=True, tokenize=True)
                    except Exception as exc:
                        # jinja TemplateError (e.g. a template's own
                        # raise_exception on bad role order) is not a
                        # ValueError — without this rewrap it would skip
                        # the 422 path AND the partial-batch cancel below
                        raise ValueError(
                            f"chat template failed: {exc}") from exc
                    is_text = True  # natural-stop eos default applies
                elif is_text:
                    if tok is None:
                        raise ValueError(
                            "text requests need a tokenizer — start the "
                            "server with --hf-model")
                    tokens = tok.encode(str(e["text"]))
                # eos default applies ONLY to text requests (natural stop);
                # the token-id API keeps exact-length semantics, and an
                # explicit "eos_token": null opts text requests out too
                if "eos_token" in e:
                    eos = e["eos_token"]
                elif is_text and tok is not None:
                    eos = tok.eos_token_id
                else:
                    eos = None
                temp = e.get("temperature")
                top_k = e.get("top_k")
                # explicit None checks: `or` would coerce the INVALID
                # top_p=0.0 to the default instead of letting the
                # engine's validation 422 it
                top_p = e.get("top_p")
                reqs.append(self.svc.submit(
                    tokens or [],
                    int(e.get("max_new_tokens") or 32),
                    eos,
                    prefix_id=e.get("prefix_id"),
                    temperature=None if temp is None else float(temp),
                    top_k=0 if top_k is None else int(top_k),
                    top_p=1.0 if top_p is None else float(top_p),
                    logprobs=_parse_bool(e.get("logprobs"), "logprobs"),
                    adapter_id=int(e.get("adapter_id") or 0),
                    stop=_parse_stop(e.get("stop"), tok),
                ))
        except (ValueError, TypeError) as e:
            # partially-submitted batch: release what already went in
            self.svc.cancel(reqs)
            return self._send(422, {"error": str(e)})
        if stream:
            return self._stream_response(reqs[0])
        if not self.svc.wait(reqs):
            # client gets a 504 and is gone; orphaned work must not keep
            # occupying slots generating tokens nobody reads
            self.svc.cancel(reqs)
            return self._send(504, {"error": "generation timed out"})
        results = []
        for r in reqs:
            entry = {"tokens": r.tokens, "request_id": r.request_id}
            if r.error:
                # engine-side failure (e.g. poisoned prefill batch): the
                # request is done with empty tokens; say why instead of
                # returning a silent empty completion
                entry["error"] = r.error
            if r.logprobs:
                entry["logprobs"] = r.token_logprobs
            if tok is not None:
                entry["text"] = tok.decode(r.tokens, skip_special_tokens=True)
            results.append(entry)
        self._send(200, results[0] if single else {"results": results})


def main(argv=None) -> int:
    args = parse_args(argv)

    from kubedl_tpu.train import coordinator

    coordinator.initialize()

    import jax

    from kubedl_tpu.models.serving import ServingEngine
    from kubedl_tpu.train.generate import resolve_params

    params, config = resolve_params(
        args.model, args.hf_model, args.checkpoint_path,
        args.allow_fresh_init, lora_checkpoint_path=args.lora_checkpoint_path,
        lora_alpha=args.lora_alpha)
    if params is None:
        return 1
    from kubedl_tpu.train.generate import load_tokenizer

    tokenizer = load_tokenizer(args.hf_model)
    if args.int8:
        from kubedl_tpu.models import quant

        params = jax.jit(quant.quantize_params)(params)
    draft_params = draft_config = None
    if args.draft_model or args.draft_hf_model or args.draft_checkpoint_path:
        if not (args.draft_hf_model or args.draft_checkpoint_path):
            # resolve_params would silently fresh-init a weightless
            # draft; random drafts floor acceptance and make serving
            # STRICTLY slower than the plain engine
            if not args.allow_fresh_init:
                print("error: --draft-model needs weights "
                      "(--draft-checkpoint-path or --draft-hf-model); "
                      "pass --allow-fresh-init to force a random draft "
                      "for tests", file=sys.stderr)
                return 1
            print("warning: random-init draft — speculation will be "
                  "slower than plain serving (test mode)", file=sys.stderr)
        draft_params, draft_config = resolve_params(
            args.draft_model or "tiny", args.draft_hf_model,
            args.draft_checkpoint_path, args.allow_fresh_init,
            label="draft")
        if draft_params is None:
            return 1
    engine = ServingEngine(
        params, config, slots=args.slots, max_len=args.max_len,
        temperature=args.temperature,
        kv_dtype="int8" if args.kv_int8 else None,
        draft_params=draft_params, draft_config=draft_config,
        spec_k=args.spec_k,
    )
    svc = _Service(engine, tokenizer=tokenizer, decode_block=args.decode_block)
    for spec in args.adapter:
        # CKPT[:ALPHA] — registration failures at startup are fatal: a
        # deployment that silently dropped an adapter would 422 every
        # request that names it
        path, _, alpha_s = spec.rpartition(":")
        if path and alpha_s.replace(".", "", 1).isdigit():
            alpha = float(alpha_s)
        else:
            path, alpha = spec, None
        try:
            aid = svc.register_adapter(path, alpha=alpha)
        except ValueError as e:
            print(f"error: --adapter {spec!r}: {e}", file=sys.stderr)
            svc.stop()
            return 1
        print(f"adapter {aid}: {path} (alpha={alpha})", flush=True)
    httpd = ThreadingHTTPServer((args.bind, args.port), _Handler)
    httpd.daemon_threads = True
    httpd.svc = svc  # type: ignore[attr-defined]
    host, port = httpd.server_address[:2]
    model_name = args.hf_model or args.model
    print(f"serving {model_name} on http://{host}:{port} "
          f"(slots={args.slots}, max_len={args.max_len})", flush=True)
    if args.max_steps:
        # smoke mode: serve in the background until N ticks happen
        import time

        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        while svc.ticks < args.max_steps:
            time.sleep(0.05)
        httpd.shutdown()
        svc.stop()
        return 0
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
