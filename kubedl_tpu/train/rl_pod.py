"""RL fleet pod entrypoint — JAXJob ``spec.rl`` (docs/rl.md).

One command for every fleet pod: the operator-injected ``KUBEDL_RL_ROLE``
dispatches to the actor or the learner main. Deliberately NOT the SPMD
trainer: fleet pods never join one jax.distributed world — the
trajectory queue and weight broadcast are the only coupling.

Usage (as a pod command):
    python -m kubedl_tpu.train.rl_pod --model tiny --steps 50

``--steps`` counts LEARNER updates; each actor runs
``ceil(steps / actors)`` generation iterations (one iteration emits
``promptsPerStep`` trajectory groups — the learner's batch).

Transports (docs/transport.md): DirChannel edges under
``KUBEDL_RL_QUEUE_DIR`` (the checkpoint volume's ``.rl`` dir) on the
local executor; the authenticated socket plane (KUBEDL_TRANSPORT=socket,
actors dial ``KUBEDL_RL_LEARNER_ADDR``, the learner dials
``KUBEDL_RL_ACTOR_ADDRS``) in kube mode. Byte-identical payloads either
way. Fleet planes keep the boot-id latch: a restarted peer is refused
loudly and the pod exits retryable, so the WHOLE gang restarts from the
learner's checkpoint instead of training against a stale incarnation.

Both roles init the base policy from the same seed, so version 0 is
identical fleet-wide without a broadcast; the learner restores its
TrainState from ``<checkpoint>/learner`` on restart and versions
restart from 0 with the gang (whole-gang restart semantics).
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
from typing import Dict, List, Optional, Tuple


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"))
    p.add_argument("--steps", type=int,
                   default=int(os.environ.get("KUBEDL_STEPS", 50)),
                   help="learner update steps")
    p.add_argument("--lr", type=float,
                   default=float(os.environ.get("KUBEDL_RL_LR", 1e-5)))
    p.add_argument("--clip-eps", type=float, default=0.2)
    p.add_argument("--kl-coef", type=float,
                   default=float(os.environ.get("KUBEDL_RL_KL_COEF", 0.04)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("KUBEDL_SEED", 0)))
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--data-path",
                   default=os.environ.get("KUBEDL_DATA_PATH", ""))
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""))
    p.add_argument("--checkpoint-interval", type=int,
                   default=int(os.environ.get("KUBEDL_CHECKPOINT_INTERVAL",
                                              0)))
    return p.parse_args(argv)


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _rl_env_config(args):
    """The fleet shape from the operator-injected env, re-validated with
    the SAME shared rule set as submit (api/validation.validate_rl_shapes)
    so a hand-run pod cannot drift past apply-time validation."""
    from kubedl_tpu.api.validation import validate_rl_shapes

    cfg = {
        "n_actors": _env_int("KUBEDL_RL_ACTORS", 1),
        "actor_index": _env_int("KUBEDL_RL_ACTOR_INDEX", 0),
        "group_size": _env_int("KUBEDL_RL_GROUP_SIZE", 8),
        "prompts_per_step": _env_int("KUBEDL_RL_PROMPTS_PER_STEP", 4),
        "max_new_tokens": _env_int("KUBEDL_RL_MAX_NEW_TOKENS", 32),
        "temperature": float(os.environ.get("KUBEDL_RL_TEMPERATURE", 1.0)),
        "max_weight_lag": _env_int("KUBEDL_RL_MAX_WEIGHT_LAG", 1),
        "broadcast_interval": _env_int("KUBEDL_RL_BROADCAST_INTERVAL", 1),
        "reward": os.environ.get("KUBEDL_RL_REWARD", "token-match"),
        "reward_token": _env_int("KUBEDL_RL_REWARD_TOKEN", 5),
        "target_len": _env_int("KUBEDL_RL_TARGET_LEN", 16),
        "eos_id": _env_int("KUBEDL_RL_EOS_ID", -1),
        "engine": os.environ.get("KUBEDL_RL_ENGINE", "decode"),
    }
    errs = validate_rl_shapes(
        cfg["n_actors"], 1, cfg["group_size"], cfg["max_weight_lag"],
        prompts_per_step=cfg["prompts_per_step"],
        max_new_tokens=cfg["max_new_tokens"],
        temperature=cfg["temperature"],
        broadcast_interval=cfg["broadcast_interval"],
        reward=cfg["reward"], eos_id=cfg["eos_id"],
        rollout_engine=cfg["engine"],
        # kubedl-analysis: allow[env-contract] error-message path label for validate_rl_shapes, not an env var read
        path="KUBEDL_RL")
    if errs:
        raise ValueError("; ".join(errs))
    return cfg


def channels_from_env(
    role: str,
    actor_ids: List[str],
    env: Optional[Dict[str, str]] = None,
):
    """(plane, role-side channels) from the injected transport env.

    Actor: ``(plane, traj_send_channel, weight_recv_channel)``.
    Learner: ``(plane, {actor: traj_recv_channel}, [weight_send_channel
    per actor])``. ``plane`` is None on the dir lane (close it on the
    socket lane when done)."""
    env = os.environ if env is None else env
    from kubedl_tpu.rl.trajectory import TRAJECTORY_CHANNEL
    from kubedl_tpu.rl.weights import WEIGHT_CHANNEL
    from kubedl_tpu.transport.plane import ENV_TRANSPORT, plane_from_env

    if env.get(ENV_TRANSPORT, "") == "socket":
        service = env.get("POD_NAME", "") or f"rl-{role}"
        plane = plane_from_env(service=service, latch=True, env=env)
        if role == "actor":
            learner_addr = env.get("KUBEDL_RL_LEARNER_ADDR", "")
            if not learner_addr:
                raise ValueError(
                    "KUBEDL_TRANSPORT=socket actor needs "
                    "KUBEDL_RL_LEARNER_ADDR")
            me = actor_ids[0]
            return (plane,
                    plane.channel(f"{TRAJECTORY_CHANNEL}.{me}",
                                  peer_addr=learner_addr),
                    plane.channel(WEIGHT_CHANNEL))
        addrs = [a for a in env.get(
            "KUBEDL_RL_ACTOR_ADDRS", "").split(",") if a]
        if len(addrs) != len(actor_ids):
            raise ValueError(
                f"KUBEDL_RL_ACTOR_ADDRS has {len(addrs)} entries for "
                f"{len(actor_ids)} actors")
        traj = {a: plane.channel(f"{TRAJECTORY_CHANNEL}.{a}")
                for a in actor_ids}
        weights = [plane.channel(WEIGHT_CHANNEL, peer_addr=addr)
                   for addr in addrs]
        return plane, traj, weights
    root = env.get("KUBEDL_RL_QUEUE_DIR", "")
    if not root:
        raise ValueError(
            "dir transport needs KUBEDL_RL_QUEUE_DIR (injected from "
            "spec.checkpoint by the JAXJob controller)")
    from kubedl_tpu.parallel.pipeline_mpmd import DirChannel

    def recv_dir(path: str) -> DirChannel:
        # the queue dir rides the PERSISTENT checkpoint volume, so a
        # crashed incarnation's undelivered messages survive the
        # whole-gang restart — and tags restart from 1, so they would be
        # consumed as CURRENT data (old-version trajectories read as
        # lag 0, stale weights adopted as version 1). Purge every dir
        # this side RECEIVES on at startup, the pipeline_runtime
        # discipline; safe against live peers because each pod purges
        # before it initializes its model, seconds before any peer's
        # first send.
        ch = DirChannel(path)
        purged = ch.purge()
        if purged:
            print(f"purged {purged} stale message(s) from a previous "
                  f"incarnation in {path}", flush=True)
        return ch

    if role == "actor":
        me = actor_ids[0]
        return (None,
                DirChannel(os.path.join(root, f"traj-{me}")),
                recv_dir(os.path.join(root, f"weights-{me}")))
    traj = {a: recv_dir(os.path.join(root, f"traj-{a}"))
            for a in actor_ids}
    weights = [DirChannel(os.path.join(root, f"weights-{a}"))
               for a in actor_ids]
    return None, traj, weights


def _base_model(args) -> Tuple:
    import jax

    from kubedl_tpu.models import llama

    config = llama.LlamaConfig.config_for(args.model)
    base = llama.init(config, jax.random.PRNGKey(args.seed))
    return config, base


def _prompts(args, config, cfg) -> List[List[int]]:
    import numpy as np

    max_prompt = config.max_seq_len - cfg["max_new_tokens"]
    if args.data_path:
        from kubedl_tpu.train.grpo import load_prompts

        return load_prompts(args.data_path, max_prompt)
    rng = np.random.default_rng(args.seed)
    n = max(cfg["prompts_per_step"] * 4, 16)
    plen = min(16, max_prompt)
    return [list(rng.integers(1, config.vocab_size, plen))
            for _ in range(n)]


def _reward_fn(args, cfg):
    """The grpo.py reward family from the injected spec (one rule set:
    train/grpo.make_reward_fn)."""
    from kubedl_tpu.train.grpo import make_reward_fn

    ns = argparse.Namespace(
        reward_module=cfg["reward"] if ":" in cfg["reward"] else "",
        reward=cfg["reward"] if ":" not in cfg["reward"] else "token-match",
        reward_token=cfg["reward_token"],
        target_len=cfg["target_len"],
        max_new_tokens=cfg["max_new_tokens"],
    )
    return make_reward_fn(ns)


def actor_main(args, cfg) -> int:
    from kubedl_tpu.obs import tracer_from_env
    from kubedl_tpu.rl.actor import ActorConfig, ActorRuntime
    from kubedl_tpu.rl.trajectory import TrajectoryProducer
    from kubedl_tpu.rl.weights import WeightReceiver
    from kubedl_tpu.transport.plane import TransportError

    job = os.environ.get("KUBEDL_LABEL_JOB_NAME",
                         os.environ.get("POD_NAME", "rl"))
    acfg = ActorConfig(
        actor_index=cfg["actor_index"], n_actors=cfg["n_actors"],
        seed=args.seed, group_size=cfg["group_size"],
        prompts_per_step=cfg["prompts_per_step"],
        max_new_tokens=cfg["max_new_tokens"],
        temperature=cfg["temperature"], eos_id=cfg["eos_id"],
        max_weight_lag=cfg["max_weight_lag"],
        lockstep=(cfg["n_actors"] == 1 and cfg["max_weight_lag"] == 0),
        engine=cfg["engine"], job=job)
    plane, traj_ch, weight_ch = channels_from_env("actor", [acfg.actor_id])
    config, base = _base_model(args)
    tracer = tracer_from_env()
    actor = ActorRuntime(
        base, config, acfg, _prompts(args, config, cfg),
        _reward_fn(args, cfg),
        producer=TrajectoryProducer(traj_ch, acfg.actor_id, job=job),
        receiver=WeightReceiver(weight_ch), tracer=tracer)
    steps = -(-args.steps // cfg["n_actors"])
    preempted = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *_: preempted.update(flag=True))
    print(f"{acfg.actor_id}: {steps} iterations x "
          f"{cfg['prompts_per_step']} groups (G={cfg['group_size']}, "
          f"K={cfg['max_new_tokens']}, engine={cfg['engine']}, "
          f"lockstep={acfg.lockstep})", flush=True)
    try:
        for it in range(1, steps + 1):
            actor.step(it)
            if preempted["flag"]:
                from kubedl_tpu.utils.exit_codes import EXIT_TPU_PREEMPTED

                print(f"{acfg.actor_id}: preempted at iteration {it}; "
                      f"exiting retryable", flush=True)
                return EXIT_TPU_PREEMPTED
    except (TransportError, TimeoutError) as e:
        # a refused incarnation / starved broadcast: the fleet is torn —
        # exit retryable so the WHOLE gang restarts from checkpoint
        from kubedl_tpu.utils.exit_codes import EXIT_TPU_PREEMPTED

        print(f"{acfg.actor_id}: transport failure: {e}", file=sys.stderr,
              flush=True)
        return EXIT_TPU_PREEMPTED
    finally:
        tracer.close()
        if plane is not None:
            plane.close()
    print(f"{acfg.actor_id}: done — {actor.tokens_generated} tokens, "
          f"final weight version {actor.weight_version}, "
          f"learner_starved={actor.learner_starved_s:.2f}s", flush=True)
    return 0


def learner_main(args, cfg) -> int:
    import time

    import jax

    from kubedl_tpu.obs import tracer_from_env
    from kubedl_tpu.rl.learner import LearnerConfig, LearnerRuntime
    from kubedl_tpu.rl.trajectory import TrajectoryConsumer
    from kubedl_tpu.rl.weights import WeightBroadcaster
    from kubedl_tpu.transport.plane import TransportError

    job = os.environ.get("KUBEDL_LABEL_JOB_NAME",
                         os.environ.get("POD_NAME", "rl"))
    actor_ids = [f"actor-{i}" for i in range(cfg["n_actors"])]
    plane, traj_channels, weight_channels = channels_from_env(
        "learner", actor_ids)
    config, base = _base_model(args)
    tracer = tracer_from_env()
    lcfg = LearnerConfig(
        prompts_per_step=cfg["prompts_per_step"],
        group_size=cfg["group_size"],
        max_weight_lag=cfg["max_weight_lag"],
        broadcast_interval=cfg["broadcast_interval"],
        lr=args.lr, clip_eps=args.clip_eps, kl_coef=args.kl_coef, job=job)
    learner = LearnerRuntime(
        base, config, lcfg,
        consumer=TrajectoryConsumer(traj_channels, job=job),
        broadcaster=WeightBroadcaster(weight_channels), tracer=tracer)

    mngr = None
    start_step = 0
    if args.checkpoint_path:
        import orbax.checkpoint as ocp

        mngr = ocp.CheckpointManager(
            os.path.join(args.checkpoint_path, "learner"),
            options=ocp.CheckpointManagerOptions(max_to_keep=2, create=True))
        latest = mngr.latest_step()
        if latest is not None and os.environ.get(
                "KUBEDL_CHECKPOINT_RESTORE", "1") == "1":
            t0 = time.perf_counter()
            abstract = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, learner.state)
            learner.state = mngr.restore(
                latest, args=ocp.args.StandardRestore(abstract))
            start_step = latest
            tracer.record("ckpt.restore",
                          duration_s=time.perf_counter() - t0, step=latest)
            print(f"learner: restored policy checkpoint at step {latest}",
                  flush=True)

    def save(step, final=False):
        if mngr is None:
            return
        import orbax.checkpoint as ocp

        t0 = time.perf_counter()
        mngr.save(step, args=ocp.args.StandardSave(learner.state))
        if final:
            mngr.wait_until_finished()
        tracer.record("ckpt.save", duration_s=time.perf_counter() - t0,
                      step=step, final=final)

    preempted = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *_: preempted.update(flag=True))
    print(f"learner: {args.steps} updates over {cfg['n_actors']} actors "
          f"(B={cfg['prompts_per_step']}, G={cfg['group_size']}, "
          f"maxWeightLag={cfg['max_weight_lag']})", flush=True)

    def on_step(step, metrics):
        if step % args.log_every == 0 or step == args.steps:
            print(f"step {step}: loss={metrics['loss']:.4f} "
                  f"reward={learner.stats.last_metrics.get('reward', 0):.3f} "
                  f"kl={metrics['kl']:.4f} "
                  f"lag_max={learner.stats.max_lag_observed} "
                  f"stale_dropped={learner.stats.stale_dropped}",
                  flush=True)
        if (args.checkpoint_interval
                and step % args.checkpoint_interval == 0):
            save(step)
        if preempted["flag"]:
            from kubedl_tpu.utils.exit_codes import EXIT_TPU_PREEMPTED

            save(step, final=True)
            print(f"learner: preempted at step {step}; exiting retryable",
                  flush=True)
            raise SystemExit(EXIT_TPU_PREEMPTED)

    try:
        stats = learner.run(args.steps - start_step, start=start_step + 1,
                            on_step=on_step)
    except (TransportError, TimeoutError, RuntimeError) as e:
        from kubedl_tpu.utils.exit_codes import EXIT_TPU_PREEMPTED

        print(f"learner: fleet failure: {e}", file=sys.stderr, flush=True)
        save(start_step, final=True)
        return EXIT_TPU_PREEMPTED
    finally:
        tracer.close()
        if plane is not None:
            plane.close()
    save(args.steps, final=True)
    print(f"learner: done — {stats.steps} steps, "
          f"consumed={stats.consumed} stale_dropped={stats.stale_dropped} "
          f"max_weight_lag_observed={stats.max_lag_observed} "
          f"actor_starved={stats.actor_starved_s:.2f}s "
          f"loss={stats.last_loss:.4f}", flush=True)
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    role = os.environ.get("KUBEDL_RL_ROLE", "")
    if role not in ("actor", "learner"):
        print(f"KUBEDL_RL_ROLE must be actor|learner (got {role!r}) — "
              f"this entrypoint runs under JAXJob spec.rl",
              file=sys.stderr)
        return 2  # permanent config error
    from kubedl_tpu.train.coordinator import _honor_platform_env

    _honor_platform_env()
    try:
        cfg = _rl_env_config(args)
    except ValueError as e:
        print(f"rl config invalid: {e}", file=sys.stderr)
        return 2
    if role == "actor":
        return actor_main(args, cfg)
    return learner_main(args, cfg)


if __name__ == "__main__":
    sys.exit(main())
