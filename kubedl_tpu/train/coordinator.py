"""Runtime-side coordinator bootstrap — the single rendezvous scheme.

The operator injects KUBEDL_COORDINATOR_ADDRESS / KUBEDL_NUM_PROCESSES /
KUBEDL_PROCESS_ID (workloads/common.py). Training programs call
`initialize()` once at startup; it wires jax.distributed so XLA collectives
ride ICI within a slice and DCN across slices — replacing the reference's
four per-framework bootstrap paths (TF_CONFIG gRPC ring, torch TCP store,
Rabit tracker, ZooKeeper; SURVEY.md §2.4).
"""
from __future__ import annotations

import logging
import os
import socket
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger("kubedl_tpu.coordinator")

ENV_COORDINATOR_ADDRESS = "KUBEDL_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KUBEDL_NUM_PROCESSES"
ENV_PROCESS_ID = "KUBEDL_PROCESS_ID"
# Multislice identity (workloads/jaxjob.py, numSlices > 1): which DCN-joined
# slice this process belongs to. The mesh layout itself comes from
# KUBEDL_DCN_MESH (parallel/mesh.py); these are for program-level use —
# logging, per-slice data sharding, profiling labels.
ENV_NUM_SLICES = "KUBEDL_NUM_SLICES"
ENV_SLICE_ID = "KUBEDL_SLICE_ID"
# Live-reshard protocol (train/reshard_runtime.py): the executor injects a
# per-pod control dir the scheduler posts RESIZE messages into; the
# operator opts jobs in via spec.elastic.liveReshard and points the gang
# at a shared staging dir for the multi-process (restart) lane. These are
# part of the SAME rendezvous contract: a resized gang re-joins the
# coordinator with the topology the staging manifest names.
ENV_CONTROL_DIR = "KUBEDL_CONTROL_DIR"
ENV_LIVE_RESHARD = "KUBEDL_LIVE_RESHARD"
ENV_RESHARD_DIR = "KUBEDL_RESHARD_DIR"


@dataclass
class ProcessInfo:
    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    num_slices: int = 1
    slice_id: int = 0
    # live-reshard wiring (empty/False when the job did not opt in)
    control_dir: str = ""
    live_reshard: bool = False
    reshard_dir: str = ""

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1


def process_info() -> ProcessInfo:
    return ProcessInfo(
        coordinator_address=os.environ.get(ENV_COORDINATOR_ADDRESS),
        num_processes=int(os.environ.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(os.environ.get(ENV_PROCESS_ID, "0")),
        num_slices=int(os.environ.get(ENV_NUM_SLICES, "1")),
        slice_id=int(os.environ.get(ENV_SLICE_ID, "0")),
        control_dir=os.environ.get(ENV_CONTROL_DIR, ""),
        live_reshard=os.environ.get(ENV_LIVE_RESHARD, "") == "1",
        reshard_dir=os.environ.get(ENV_RESHARD_DIR, ""),
    )


def _resolve_local(address: str) -> str:
    """Map service-DNS coordinator addresses to loopback when the headless
    DNS name doesn't resolve (local executor mode: all processes share one
    host, so the coordination service is reachable on 127.0.0.1)."""
    host, _, port = address.partition(":")
    try:
        socket.getaddrinfo(host, None)
        return address
    except socket.gaierror:
        return f"127.0.0.1:{port or '8471'}"


def _honor_platform_env() -> None:
    """Make JAX_PLATFORMS=cpu authoritative even when a sitecustomize has
    already pinned a different platform programmatically (config beats env
    in JAX). Test/CI pods set the env to get the hermetic virtual-device
    CPU mesh; without this they would silently dial the real accelerator."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want != "cpu":
        return
    import jax

    if (jax.config.jax_platforms or "") == "cpu":
        return
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()


def initialize(info: Optional[ProcessInfo] = None) -> ProcessInfo:
    """Idempotently initialize jax.distributed from the injected env."""
    _honor_platform_env()
    info = info or process_info()
    if not info.is_distributed or info.coordinator_address is None:
        return info
    import jax

    addr = _resolve_local(info.coordinator_address)
    try:
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=info.num_processes,
            process_id=info.process_id,
        )
        log.info(
            "jax.distributed initialized: %d/%d via %s",
            info.process_id, info.num_processes, addr,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise
    return info
