"""DPO post-training workload — preference pairs in, aligned policy out.

JAXJob-deployable CLI over train/preference.py: reads JSONL preference
data, runs the sharded DPO step (mesh from KUBEDL_MESH like the
trainer), checkpoints the FULL policy TrainState (so generate/serve
restore it with the ordinary --checkpoint-path), and logs the implicit
reward margin + preference accuracy.

Data format — one JSON object per line:

    {"prompt": [ids...], "chosen": [ids...], "rejected": [ids...]}

With --hf-model the fields may also be raw strings, encoded by the
checkpoint's own tokenizer.

Pairs are right-padded to --seq-len (prompt + longer continuation must
fit). The frozen reference is the STARTING policy (base weights from
--hf-model / --ref-checkpoint-path / fresh init), the standard DPO
setup; its logprobs are computed once per unique batch and cached.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubedl-dpo")
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"),
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"])
    p.add_argument("--hf-model", default=os.environ.get("KUBEDL_HF_MODEL", ""),
                   help="Hugging Face base weights (policy AND reference init)")
    p.add_argument("--ref-checkpoint-path", default="",
                   help="trainer Orbax dir for the base weights (else fresh "
                        "init / --hf-model)")
    p.add_argument("--data-path", default=os.environ.get("KUBEDL_DATA_PATH", ""),
                   help="JSONL preference pairs; synthetic pairs when empty "
                        "(smoke/bench)")
    p.add_argument("--steps", type=int, default=int(os.environ.get("KUBEDL_STEPS", 100)))
    p.add_argument("--batch", type=int, default=int(os.environ.get("KUBEDL_BATCH", 8)))
    p.add_argument("--seq-len", type=int, default=int(os.environ.get("KUBEDL_SEQ_LEN", 512)))
    p.add_argument("--lr", type=float, default=5e-7)
    p.add_argument("--beta", type=float, default=0.1)
    p.add_argument("--grad-clip", type=float, default=1.0)
    p.add_argument("--accum-steps", type=int, default=1)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""))
    p.add_argument("--checkpoint-interval", type=int, default=200)
    p.add_argument("--allow-fresh-init", action="store_true",
                   help="train from random base weights when no "
                        "--hf-model/--ref-checkpoint-path weights exist "
                        "(otherwise that's an error — DPO over a random "
                        "policy is never what a deployed job means)")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def load_pairs(path: str, seq_len: int, tokenizer=None):
    """JSONL -> (tokens [n,2,T], prompt_lens [n], seq_lens [n,2]); pairs
    that cannot fit seq_len are skipped with a count. Fields may be id
    lists or (with a tokenizer) raw strings."""
    import numpy as np

    from kubedl_tpu.train.generate import encode_field

    toks, plens, slens = [], [], []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            prompt = encode_field(rec["prompt"], tokenizer, "prompt")
            chosen = prompt + encode_field(
                rec["chosen"], tokenizer, "chosen", continuation=True)
            rejected = prompt + encode_field(
                rec["rejected"], tokenizer, "rejected", continuation=True)
            if (max(len(chosen), len(rejected)) > seq_len
                    or len(prompt) < 1
                    or len(chosen) == len(prompt)
                    or len(rejected) == len(prompt)):
                # empty continuations make one logprob side hard-zero —
                # a degenerate gradient, not a preference
                skipped += 1
                continue
            row = np.zeros((2, seq_len), np.int32)
            row[0, :len(chosen)] = chosen
            row[1, :len(rejected)] = rejected
            toks.append(row)
            plens.append(len(prompt))
            slens.append([len(chosen), len(rejected)])
    if not toks:
        raise ValueError(f"no usable pairs in {path!r} at seq_len {seq_len}")
    if skipped:
        print(f"data: skipped {skipped} pairs exceeding --seq-len {seq_len}",
              flush=True)
    return (np.stack(toks), np.asarray(plens, np.int32),
            np.asarray(slens, np.int32))


def main(argv=None) -> int:
    args = parse_args(argv)

    from kubedl_tpu.train import coordinator

    coordinator.initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubedl_tpu.models import llama
    from kubedl_tpu.parallel.mesh import ShardingRules, build_mesh_from_env
    from kubedl_tpu.train.preference import make_dpo_step

    tokenizer = None
    if args.hf_model:
        from kubedl_tpu.models.import_hf import load_hf

        base, config = load_hf(args.hf_model)
        from kubedl_tpu.train.generate import load_tokenizer

        tokenizer = load_tokenizer(args.hf_model)
    else:
        config = llama.LlamaConfig.config_for(args.model)
        from kubedl_tpu.train.generate import restore_or_init

        base = restore_or_init(
            config, args.ref_checkpoint_path,
            allow_fresh_init=(args.allow_fresh_init
                              or not args.ref_checkpoint_path),
            seed=args.seed, label="base")
        if base is None:
            return 1
    mesh = build_mesh_from_env()
    rules = ShardingRules()
    print(f"mesh: {dict(mesh.shape)} model={args.hf_model or args.model} "
          f"beta={args.beta}", flush=True)

    tx = optax.adamw(args.lr, weight_decay=0.0)
    if args.grad_clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(args.grad_clip), tx)
    init_state, ref_fn, step = make_dpo_step(
        base, config, tx, mesh, rules=rules, beta=args.beta,
        accum_steps=args.accum_steps,
    )
    state = init_state(jax.tree.map(jnp.asarray, base))
    del base

    # data: whole-set host arrays (preference sets are small relative to
    # pretraining corpora); batches cycle with a seeded permutation
    rng = np.random.default_rng(args.seed)
    if args.data_path:
        tokens, plens, slens = load_pairs(args.data_path, args.seq_len,
                                          tokenizer=tokenizer)
        print(f"data: {len(tokens)} pairs from {args.data_path}", flush=True)
    else:
        n = max(args.batch * 4, 32)
        tokens = rng.integers(
            1, config.vocab_size, (n, 2, args.seq_len)).astype(np.int32)
        plens = rng.integers(1, max(args.seq_len // 4, 2), (n,)).astype(np.int32)
        slens = rng.integers(
            args.seq_len // 2, args.seq_len + 1, (n, 2)).astype(np.int32)
        for i in range(n):  # shared prompt across each pair
            tokens[i, 1, :plens[i]] = tokens[i, 0, :plens[i]]
        print(f"data: {n} synthetic pairs (no --data-path)", flush=True)

    mngr = None
    start_step = 0
    if args.checkpoint_path:
        import orbax.checkpoint as ocp

        mngr = ocp.CheckpointManager(
            args.checkpoint_path,
            options=ocp.CheckpointManagerOptions(max_to_keep=2, create=True),
        )
        latest = mngr.latest_step()
        if latest is not None:
            # preemption resume: restore into the SHARDED state and pick
            # the schedule up where it stopped (trainer.py's pattern)
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
            state = mngr.restore(latest, args=ocp.args.StandardRestore(abstract))
            start_step = latest
            print(f"restored policy checkpoint at step {start_step}", flush=True)

    n_pairs = len(tokens)
    order = rng.permutation(n_pairs)
    ref_cache = {}
    import time

    t0 = time.time()
    for it in range(start_step + 1, args.steps + 1):
        lo = ((it - 1) * args.batch) % n_pairs
        idx = np.take(order, range(lo, lo + args.batch), mode="wrap")
        batch = (jnp.asarray(tokens[idx]), jnp.asarray(plens[idx]),
                 jnp.asarray(slens[idx]))
        key = (lo, args.batch)
        if key not in ref_cache:
            ref_cache[key] = ref_fn(batch)
        state, metrics = step(state, (*batch, ref_cache[key]))
        if it % args.log_every == 0 or it == args.steps:
            m = {k: float(v) for k, v in metrics.items()}
            print(f"step {it}: loss={m['loss']:.4f} "
                  f"margin={m['reward_margin']:.3f} "
                  f"acc={m['preference_accuracy']:.2f}", flush=True)
        if mngr is not None and (it % args.checkpoint_interval == 0
                                 or it == args.steps):
            import orbax.checkpoint as ocp

            mngr.save(it, args=ocp.args.StandardSave(state))
    if mngr is not None:
        mngr.wait_until_finished()
        print(f"saved policy checkpoint at step {args.steps}", flush=True)
    print(f"done: {args.steps - start_step} steps in "
          f"{time.time() - t0:.1f}s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
