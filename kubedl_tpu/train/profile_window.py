"""Shared JAX-profiler window for the trainer entrypoints.

One class owns the ``--profile-dir`` start/stop discipline so the SPMD
trainer (train/trainer.py) and the MPMD stage trainer
(train/pipeline_trainer.py) cannot drift: the trace covers
``[start_step+1, start_step+1+n_steps)`` — skipping the compile step —
and ``stop()`` is

  * idempotent: the flag flips BEFORE the profiler call, so the SIGTERM
    preemption path, the end-of-loop path, and the ``finally`` backstop
    can all call it without a double-stop error;
  * exception-safe: a profiler that refuses to stop (e.g. it already
    tore down during interpreter shutdown) logs and moves on — a trace
    hiccup must never turn a clean checkpoint exit into a crash.

The ``finally`` backstop matters for SIGTERM *during* the traced window:
the preemption flag is polled after each step, but a step that raises
while tracing would otherwise leave the profiler open past os._exit and
drop the trace.
"""
from __future__ import annotations

import sys
from typing import Optional


class ProfileWindow:
    def __init__(
        self,
        profile_dir: str,
        start_step: int,
        n_steps: int = 5,
        profiler=None,
    ) -> None:
        self.profile_dir = profile_dir
        # [start+1, start+1+n): skip the compile step
        self.start_at = start_step + 1 if profile_dir else -1
        self.stop_after = self.start_at + max(n_steps, 1)
        self.tracing = False
        self._profiler = profiler  # test seam; None = jax.profiler, lazily

    def _jax_profiler(self):
        if self._profiler is None:
            import jax

            self._profiler = jax.profiler
        return self._profiler

    def maybe_start(self, step: int) -> None:
        """Call at the TOP of the step loop, before dispatching the step."""
        if step == self.start_at and not self.tracing:
            self.tracing = True
            try:
                self._jax_profiler().start_trace(self.profile_dir)
            except Exception as e:  # noqa: BLE001 — profiling is best-effort
                self.tracing = False
                print(f"profiler start failed: {e}", file=sys.stderr)

    def should_stop(self, step: int) -> bool:
        """True when the step just completed closes the traced window
        (the caller syncs the device before stop() so the trace holds
        finished work, not in-flight dispatches)."""
        return self.tracing and step + 1 >= self.stop_after

    def stop(self) -> None:
        """Idempotent, exception-safe stop — safe from the preemption
        path, the normal end, and the finally backstop alike."""
        if not self.tracing:
            return
        self.tracing = False  # flip FIRST: re-entry must be a no-op
        try:
            self._jax_profiler().stop_trace()
            print(f"profile written to {self.profile_dir}", flush=True)
        except Exception as e:  # noqa: BLE001 — trace loss must not crash exit
            print(f"profiler stop failed: {e}", file=sys.stderr)


def window_from_args(args, start_step: int,
                     profiler=None) -> Optional[ProfileWindow]:
    """ProfileWindow from the shared --profile-dir/--profile-steps flags;
    None when profiling is off."""
    profile_dir = getattr(args, "profile_dir", "")
    if not profile_dir:
        return None
    return ProfileWindow(
        profile_dir, start_step,
        n_steps=getattr(args, "profile_steps", 5), profiler=profiler)
