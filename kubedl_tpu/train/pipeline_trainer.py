"""MPMD pipeline stage trainer — the pod entrypoint for JAXJob
`spec.pipeline.mpmd` (docs/pipeline.md).

Each pod runs ONE stage program built from the operator-injected
KUBEDL_PP_* env (train/pipeline_runtime.runtime_from_env): its layer
chunk + optimizer state, the 1F1B loop, and the serialized boundary
channels to its ring neighbors. Deliberately NOT the SPMD trainer:
stages never join one jax.distributed world — the boundary channel is
the only coupling (which is the point: no global barrier, no Megascale).

The endpoint stages (first and last) drive the data; this entrypoint
feeds the same synthetic next-token stream the SPMD trainer defaults to
(seeded identically on both endpoints so inputs and targets line up).
Checkpointing is stage-local: each stage saves {params, opt_state} under
<checkpoint>/stage-<i>/ on its own Orbax manager, restores on restart,
and banks a final save on SIGTERM — the whole-gang restart semantics of
the SPMD trainer, per stage.

Usage (as a pod command):
    python -m kubedl_tpu.train.pipeline_trainer --model tiny --steps 100

The boundary transport is env-selected (docs/transport.md): DirChannel
over KUBEDL_PP_BOUNDARY_DIR on the local executor, the authenticated
socket plane (KUBEDL_TRANSPORT=socket + KUBEDL_PP_PREV/NEXT_ADDR) in
kube mode — byte-identical boundary payloads either way.

Limitations (documented in docs/pipeline.md): one process per stage
(a stage spanning multiple hosts would need per-stage jax.distributed
wiring on top), synthetic data only (--data-path is refused rather
than silently ignored).
"""
from __future__ import annotations

import argparse
import os
import signal
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"))
    p.add_argument("--steps", type=int,
                   default=int(os.environ.get("KUBEDL_STEPS", 100)))
    p.add_argument("--batch", type=int,
                   default=int(os.environ.get("KUBEDL_BATCH", 8)))
    p.add_argument("--seq-len", type=int,
                   default=int(os.environ.get("KUBEDL_SEQ_LEN", 512)))
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--data-path",
                   default=os.environ.get("KUBEDL_DATA_PATH", ""))
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""))
    p.add_argument("--checkpoint-interval", type=int,
                   default=int(os.environ.get("KUBEDL_CHECKPOINT_INTERVAL", 0)))
    # JAX profiler window, same contract as the SPMD trainer
    # (train/profile_window.py): N steps after the compile step, stopped
    # cleanly on preemption too
    p.add_argument("--profile-dir",
                   default=os.environ.get("KUBEDL_PROFILE_DIR", ""))
    p.add_argument("--profile-steps", type=int,
                   default=int(os.environ.get("KUBEDL_PROFILE_STEPS", 5)))
    return p.parse_args(argv)


def _common_restore_step(ckpt_path: str, n_stages: int):
    """Latest checkpoint step present in EVERY stage's dir (None = some
    stage has none — the gang starts fresh together; identical init
    seeds keep that consistent). A step dir mid-write fails the restore
    loudly rather than resuming on a partial save."""
    steps = None
    for s in range(n_stages):
        d = os.path.join(ckpt_path, f"stage-{s}")
        try:
            have = {int(x) for x in os.listdir(d) if x.isdigit()}
        except OSError:
            return None
        steps = have if steps is None else steps & have
        if not steps:
            return None
    return max(steps)


def main(argv=None) -> int:
    import time

    t_main0 = time.perf_counter()
    args = parse_args(argv)
    if args.data_path:
        print("pipeline_trainer supports synthetic data only for now "
              "(--data-path would need per-endpoint shard loaders)",
              file=sys.stderr)
        return 2  # permanent config error (utils/exit_codes.py)

    from kubedl_tpu.train.coordinator import _honor_platform_env

    _honor_platform_env()

    import jax
    import numpy as np
    import optax

    from kubedl_tpu.models import llama
    from kubedl_tpu.train import pipeline_runtime
    from kubedl_tpu.utils.exit_codes import EXIT_TPU_PREEMPTED

    config = llama.LlamaConfig.config_for(args.model)
    stage = int(os.environ.get("KUBEDL_PP_STAGE", "0"))
    n_stages = int(os.environ.get("KUBEDL_PP_STAGES", "1"))

    # flight recorder (docs/observability.md): per-stage step spans +
    # telemetry stream, correlated by the injected gang trace id — the
    # MPMD plane's pods share the job's KUBEDL_TRACE_DIR
    from kubedl_tpu.obs import StepStream, tracer_from_env

    tracer = tracer_from_env()
    step_stream = StepStream.from_env()
    tx = optax.adamw(args.lr, weight_decay=0.01)
    try:
        rt = pipeline_runtime.runtime_from_env(
            config, llama.init(config, jax.random.PRNGKey(0)), tx)
    except ValueError as e:
        print(f"pipeline config invalid: {e}", file=sys.stderr)
        return 2
    endpoint = stage == 0 or stage == n_stages - 1
    print(f"stage {stage}/{n_stages}: layers "
          f"{rt.plan.layer_range(stage)} of {config.n_layers}, "
          f"microbatches={rt.plan.n_microbatches}, "
          f"{'endpoint (drives data)' if endpoint else 'middle'}",
          flush=True)

    # stage-local Orbax checkpoint: {params, opt_state, step}
    mngr = None
    start_step = 0
    if args.checkpoint_path:
        import orbax.checkpoint as ocp

        mngr = ocp.CheckpointManager(
            os.path.join(args.checkpoint_path, f"stage-{stage}"),
            options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True))
        # Restore the latest step EVERY stage has, not this stage's own
        # latest: interval saves are per-stage and a crash can land
        # between them, so stages' latest steps may differ — restoring
        # independently would silently resume the gang at inconsistent
        # optimizer steps (and deadlock the tail, which expects equal
        # remaining step counts). The stage dirs share the checkpoint
        # volume, so every stage can compute the same common step.
        restore = _common_restore_step(args.checkpoint_path, n_stages)
        if restore is not None and os.environ.get(
                "KUBEDL_CHECKPOINT_RESTORE", "1") == "1":
            t_restore0 = time.perf_counter()
            target = {"params": rt.params, "opt_state": rt.opt_state}
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
            restored = mngr.restore(
                restore, args=ocp.args.StandardRestore(abstract))
            rt.params, rt.opt_state = restored["params"], restored["opt_state"]
            start_step = restore
            tracer.record("ckpt.restore",
                          duration_s=time.perf_counter() - t_restore0,
                          step=restore, stage=stage)
            own = mngr.latest_step()
            note = f" (own latest {own})" if own != restore else ""
            print(f"stage {stage}: restored gang-common checkpoint at "
                  f"step {restore}{note}", flush=True)

    ckpt_stall = {"v": 0.0}

    def save(step, final=False):
        if mngr is None:
            return
        import orbax.checkpoint as ocp

        t_save0 = time.perf_counter()
        mngr.save(step, args=ocp.args.StandardSave(
            {"params": rt.params, "opt_state": rt.opt_state}))
        if final:
            mngr.wait_until_finished()
            print(f"stage {stage}: saved final checkpoint at step {step}",
                  flush=True)
        stall = time.perf_counter() - t_save0
        ckpt_stall["v"] += stall
        tracer.record("ckpt.save", duration_s=stall, step=step, stage=stage,
                      final=final)

    preempted = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *_: preempted.update(flag=True))

    # the SPMD trainer's profiler window, previously missing here
    # entirely: N steps after the compile step, stopped idempotently on
    # the preemption path and the finally backstop
    from kubedl_tpu.train.profile_window import window_from_args

    prof = window_from_args(args, start_step)

    tracer.record("trainer.init",
                  duration_s=time.perf_counter() - t_main0,
                  step=start_step, stage=stage, model=args.model)

    rng = np.random.default_rng(1234)  # same stream on BOTH endpoints
    step = start_step
    try:
        for step in range(start_step, args.steps):
            if prof is not None:
                prof.maybe_start(step)
            tokens = None
            if endpoint:
                tokens = rng.integers(
                    0, config.vocab_size,
                    (args.batch, args.seq_len), dtype=np.int32)
            out = rt.run_step(tokens)
            if tracer.exporting or step_stream is not None:
                tracer.record(
                    "train.compile" if step == start_step else "pipeline.step",
                    duration_s=out["step_s"], step=step + 1, stage=stage,
                    wait_s=round(out["wait_s"], 6),
                    **({"loss": out["loss"]} if out["loss"] is not None
                       else {}))
                if step_stream is not None:
                    step_stream.record(
                        step + 1, out["step_s"], data_s=out["wait_s"],
                        loss=out["loss"], compile=step == start_step,
                        ckpt_s=ckpt_stall["v"])
                    ckpt_stall["v"] = 0.0
            if prof is not None and prof.should_stop(step):
                prof.stop()
            if out["loss"] is not None and (
                    step % args.log_every == 0 or step == args.steps - 1):
                print(f"step {step}: loss={out['loss']:.4f} "
                      f"step_s={out['step_s']:.3f} "
                      f"wait_s={out['wait_s']:.3f}", flush=True)
            if (args.checkpoint_interval
                    and (step + 1) % args.checkpoint_interval == 0):
                save(step + 1)
            if preempted["flag"]:
                if prof is not None:
                    prof.stop()
                save(step + 1, final=True)
                tracer.record("trainer.preempted", step=step + 1, stage=stage)
                print(f"stage {stage}: preempted at step {step + 1}; "
                      f"exiting retryable", flush=True)
                return EXIT_TPU_PREEMPTED
    finally:
        # SIGTERM/raise DURING the traced window must still stop the
        # profiler (idempotent: the paths above may have stopped already)
        if prof is not None:
            prof.stop()
        rt.close()
    save(args.steps, final=True)
    tracer.record("trainer.done", step=args.steps, stage=stage)
    if step_stream is not None:
        step_stream.close()
    tracer.close()
    print(f"stage {stage}: done at step {args.steps}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
