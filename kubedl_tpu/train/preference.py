"""Preference optimization (DPO) — post-training on the same machinery.

Direct Preference Optimization (Rafailov et al., 2023): given pairs of
(chosen, rejected) continuations for a shared prompt, push the policy's
log-ratio over a frozen reference model apart by the preference margin:

    L = -log sigmoid(beta * ((pi_c - ref_c) - (pi_r - ref_r)))

Built the same TPU-first way as pretraining (train/trainer.py): pure
loss function over the Llama backbone, sharded through
parallel/train_step.make_train_step, so dp/fsdp/tp meshes and grad
accumulation apply unchanged. Reference logprobs are computed ONCE per
batch outside the gradient (stop-gradient by construction) with the
same forward — no second backward, no reference optimizer state — and
the reference tree is SHARDED like the policy, passed as a jit argument
(a closure capture would bake a replicated copy into the executable).

MoE configs keep their router load-balancing term: the policy forward
returns the aux loss and dpo_loss adds `moe_aux_coef * aux`, matching
pretraining's llama.loss_fn.

`config.ce_chunks > 1` computes per-token target logprobs with an
online-logsumexp over vocab chunks instead of materializing the
[b, T, V] f32 log-softmax — the same memory knob the pretraining CE
uses, indispensable at DPO's 2x-batch (pair) footprint.

Batch layout: tokens [b, 2, T] int32 (dim 1 = chosen|rejected),
`prompt_lens` [b] marking where continuations start — prompt positions
are excluded from the sequence logprob, pad positions (after
`seq_lens`) likewise.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from kubedl_tpu.models import llama

NEG_INF = -1e30


def _target_logprobs_chunked(x, params, config, targets):
    """log p(targets) at each position without [.., V] logits: online
    logsumexp over `config.ce_chunks` vocab chunks + an in-chunk gather
    of the target logit. x [n, t, d] f32-castable, targets [n, t]."""
    head = llama._head_matrix(params, config)  # [d, V]
    # x arrives PRE-norm from the backbone; the head path applies the
    # final RMSNorm first (llama._lm_head does the same)
    x = llama.rms_norm(x, params["final_norm"], config.rms_eps,
                       config.norm_offset)
    v = head.shape[1]
    chunks = config.ce_chunks
    csize = -(-v // chunks)
    m = jnp.full(targets.shape, NEG_INF, jnp.float32)
    s = jnp.zeros(targets.shape, jnp.float32)
    tgt = jnp.zeros(targets.shape, jnp.float32)
    for i in range(chunks):
        lo = i * csize
        hi = min(lo + csize, v)
        logits = jnp.einsum(
            "ntd,dv->ntv", x, head[:, lo:hi].astype(x.dtype),
            preferred_element_type=jnp.float32,
        )
        if config.final_logit_softcap:
            # elementwise cap per chunk == capping the full logits; the
            # policy/reference logprobs must match the distribution the
            # decode stack (capped _lm_head) actually samples from
            logits = llama.softcap(logits, config.final_logit_softcap)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1)
        m = m_new
        idx = targets - lo
        in_chunk = (idx >= 0) & (idx < hi - lo)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, hi - lo - 1)[..., None], axis=-1)[..., 0]
        tgt = jnp.where(in_chunk, picked, tgt)
    return tgt - (m + jnp.log(s))


def _pair_logprobs(
    params: Dict,
    tokens: jax.Array,  # [b, 2, T]
    prompt_lens: jax.Array,  # [b]
    seq_lens: jax.Array,  # [b, 2]
    config: llama.LlamaConfig,
    mesh=None,
    rules=None,
) -> Tuple[jax.Array, jax.Array]:
    """([b, 2] continuation logprobs, MoE aux loss). THE single place the
    [b, 2, T] -> [2b, T] pair layout is flattened — policy and reference
    must share it or chosen/rejected silently misalign."""
    b, _, t = tokens.shape
    flat = tokens.reshape(b * 2, t)
    lp = sequence_logprobs(
        params, flat, jnp.repeat(prompt_lens, 2), seq_lens.reshape(-1),
        config, mesh=mesh, rules=rules, with_aux=True,
    )
    lp, aux = lp
    return lp.reshape(b, 2), aux


def sequence_logprobs(
    params: Dict,
    tokens: jax.Array,  # [n, T] int32
    prompt_lens: jax.Array,  # [n] int32 — continuation starts here
    seq_lens: jax.Array,  # [n] int32 — true length incl. prompt
    config: llama.LlamaConfig,
    mesh=None,
    rules=None,
    with_aux: bool = False,
    per_token: bool = False,
):
    """Sum log p(token_i | <i) over continuation positions — [n] f32
    (+ the MoE aux loss when with_aux). per_token=True skips the sum and
    returns ([n, T-1] logprobs, [n, T-1] f32 continuation mask) instead —
    the shape GRPO's per-token importance ratios need (train/rl.py)."""
    rules_ = rules
    x, aux = llama._backbone(params, tokens, config, mesh, rules_ or
                             llama.ShardingRules())
    targets = tokens[:, 1:]
    head_is_plain = isinstance(
        llama._head_matrix(params, config), jax.Array)
    if config.ce_chunks > 1 and head_is_plain:
        pred = _target_logprobs_chunked(x[:, :-1], params, config, targets)
    else:
        logits = llama._lm_head(x, params, config).astype(jnp.float32)
        logps = jax.nn.log_softmax(logits, axis=-1)
        pred = jnp.take_along_axis(
            logps[:, :-1], targets[..., None], axis=-1)[..., 0]  # [n, T-1]
    pos = jnp.arange(tokens.shape[1] - 1)[None, :]
    # target token at position i+1 belongs to the continuation iff
    # i+1 >= prompt_len and i+1 < seq_len
    mask = (pos + 1 >= prompt_lens[:, None]) & (pos + 1 < seq_lens[:, None])
    if per_token:
        out = (pred, mask.astype(jnp.float32))
    else:
        out = jnp.sum(pred * mask, axis=-1)
    return (out, aux) if with_aux else out


def dpo_loss(
    params: Dict,
    ref_logprobs: jax.Array,  # [b, 2] — precomputed reference logprobs
    tokens: jax.Array,  # [b, 2, T]
    prompt_lens: jax.Array,  # [b]
    seq_lens: jax.Array,  # [b, 2]
    config: llama.LlamaConfig,
    beta: float = 0.1,
    mesh=None,
    rules=None,
) -> Tuple[jax.Array, Dict]:
    """(scalar loss, metrics) — metrics carry the implicit reward margin
    and preference accuracy, the numbers worth plotting."""
    lp, aux = _pair_logprobs(
        params, tokens, prompt_lens, seq_lens, config, mesh=mesh, rules=rules)
    pi_ratio = lp[:, 0] - lp[:, 1]
    ref_ratio = ref_logprobs[:, 0] - ref_logprobs[:, 1]
    margin = beta * (pi_ratio - ref_ratio)
    loss = jnp.mean(-jax.nn.log_sigmoid(margin))
    if config.n_experts > 0:
        # router balance term, same coefficient as pretraining — dropping
        # it for the whole DPO phase invites expert collapse
        loss = loss + config.moe_aux_coef * aux
    metrics = {
        "reward_margin": jnp.mean(margin),
        "preference_accuracy": jnp.mean((margin > 0).astype(jnp.float32)),
        "chosen_logprob": jnp.mean(lp[:, 0]),
        "rejected_logprob": jnp.mean(lp[:, 1]),
    }
    return loss, metrics


def make_dpo_step(
    ref_params: Dict,
    config: llama.LlamaConfig,
    tx,
    mesh,
    rules=None,
    beta: float = 0.1,
    param_spec_tree=None,
    accum_steps: int = 1,
):
    """(init_state, ref_logprob_fn, dpo_step) over the mesh.

    `ref_logprob_fn(batch) -> [b, 2]` runs the FROZEN reference once per
    batch (jitted, no grad); `dpo_step(state, batch_with_ref_lp)` is the
    donated sharded update. Splitting the two keeps the reference
    forward out of the differentiated graph entirely.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubedl_tpu.parallel.mesh import ShardingRules
    from kubedl_tpu.parallel.train_step import make_train_step

    rules = rules or ShardingRules()
    if param_spec_tree is None:
        param_spec_tree = llama.param_specs(config, rules)
    param_sharding = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
    # the reference tree is an ARGUMENT with explicit shardings — a jit
    # closure would bake a fully-replicated copy into the executable,
    # OOMing exactly at the scales DPO targets
    ref_sharded = jax.device_put(ref_params, param_sharding)

    @jax.jit
    def _ref_fn(ref, batch):
        tokens, prompt_lens, seq_lens = batch
        lp, _ = _pair_logprobs(
            ref, tokens, prompt_lens, seq_lens, config, mesh=mesh, rules=rules)
        return lp

    def ref_logprob_fn(batch):
        return _ref_fn(ref_sharded, batch)

    def loss_fn(params, batch):
        tokens, prompt_lens, seq_lens, ref_lp = batch
        return dpo_loss(
            params, ref_lp, tokens, prompt_lens, seq_lens, config,
            beta=beta, mesh=mesh, rules=rules,
        )

    batch_spec = (
        rules.spec("batch", None, None),  # tokens [b, 2, T]
        rules.spec("batch"),              # prompt_lens [b]
        rules.spec("batch", None),        # seq_lens [b, 2]
        rules.spec("batch", None),        # ref logprobs [b, 2]
    )
    init_state, train_step = make_train_step(
        loss_fn, tx, mesh, param_spec_tree, batch_spec, rules,
        accum_steps=accum_steps, has_aux=True,
    )
    return init_state, ref_logprob_fn, train_step
