"""Evaluation workload — checkpoint in, perplexity out.

Completes the train -> eval -> serve triad as a standalone JAXJob
program: restores params exactly like generate/serve (trainer Orbax
checkpoint, HF import, or LoRA merge), runs the SHARDED forward
(mesh from KUBEDL_MESH) over token shards with the same native
mmap+prefetch loader the trainer uses, and prints one JSON line —
token-level NLL and perplexity — the number a training run is judged
by. Unlike the trainer's interleaved --eval-every probes, this scores
a full deterministic pass (batch i = loader.batch_at(i)), so two
checkpoints are comparable bit-for-bit.

The reference operator has no evaluation (or any model) code; this is
another workload program its JAXJob equivalent deploys (ref parity
anchor: the pod-command slot in /root/reference/controllers/).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubedl-evaluate")
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"),
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"])
    p.add_argument("--hf-model", default=os.environ.get("KUBEDL_HF_MODEL", ""),
                   help="Hugging Face weights — overrides --model/--checkpoint-path")
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""),
                   help="trainer Orbax dir; newest step's params are used")
    p.add_argument("--lora-checkpoint-path", default="",
                   help="merge the newest adapter checkpoint into the base "
                        "weights before scoring (models/lora.py)")
    p.add_argument("--lora-alpha", type=float, default=None)
    p.add_argument("--allow-fresh-init", action="store_true",
                   help="score random weights when no checkpoint exists "
                        "(smoke only — otherwise that's an error)")
    p.add_argument("--data-path", default=os.environ.get("KUBEDL_DATA_PATH", ""),
                   help="glob of token shard files (trainer format); "
                        "synthetic tokens when empty (smoke only)")
    p.add_argument("--batch", type=int, default=int(os.environ.get("KUBEDL_BATCH", 8)))
    p.add_argument("--seq-len", type=int, default=int(os.environ.get("KUBEDL_SEQ_LEN", 1024)))
    p.add_argument("--max-batches", type=int, default=0,
                   help="cap scored batches (0 = the full pass)")
    p.add_argument("--log-every", type=int, default=20)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)

    from kubedl_tpu.train import coordinator

    coordinator.initialize()

    import glob as globlib
    import math
    import time

    import jax
    import numpy as np

    from kubedl_tpu.models import llama
    from kubedl_tpu.parallel.mesh import ShardingRules, build_mesh_from_env, shard_pytree
    from kubedl_tpu.train.generate import resolve_params

    params, config = resolve_params(
        args.model, args.hf_model, args.checkpoint_path,
        args.allow_fresh_init, lora_checkpoint_path=args.lora_checkpoint_path,
        lora_alpha=args.lora_alpha, seed=args.seed, label="eval")
    if params is None:
        return 1

    mesh = build_mesh_from_env()
    rules = ShardingRules()
    params = shard_pytree(params, mesh, llama.param_specs(config, rules))
    n_proc = jax.process_count()
    rank = jax.process_index()
    print(f"mesh: {dict(mesh.shape)} model={args.hf_model or args.model} "
          f"seq={args.seq_len} processes={n_proc}", flush=True)

    eval_step = jax.jit(
        lambda p, batch: llama.loss_fn(p, batch, config, mesh=mesh,
                                       rules=rules))

    # each process loads its OWN args.batch rows; the global batch is
    # n_proc * batch, assembled like the trainer's multi-host pipeline
    global_batch = args.batch * n_proc
    if args.data_path:
        from kubedl_tpu.native.loader import TokenLoader

        shard_paths = sorted(globlib.glob(args.data_path))
        if not shard_paths:
            print(f"no shards match {args.data_path!r}", file=sys.stderr)
            return 1
        loader = TokenLoader(shard_paths, batch=args.batch,
                             seq_len=args.seq_len, seed=args.seed,
                             n_threads=0)  # random access = deterministic
        if loader.n_windows < global_batch:
            # batch_at wraps window ids modulo n_windows: short sets
            # would score some windows twice and bias the mean
            print(f"only {loader.n_windows} windows for a global batch "
                  f"of {global_batch} — shrink --batch", file=sys.stderr)
            return 1
        n_batches = loader.n_windows // global_batch
        dropped = loader.n_windows - n_batches * global_batch
        if dropped:
            print(f"note: dropping {dropped} remainder windows "
                  f"(static batch shapes)", flush=True)
        # rank-strided ids: process r scores batches r, r+P, r+2P, ...
        get = lambda i: loader.batch_at(i * n_proc + rank)  # noqa: E731
        print(f"data: {len(shard_paths)} shards, {loader.n_windows} "
              f"windows -> {n_batches} global batches", flush=True)
    else:
        rng = np.random.default_rng(args.seed + rank)
        fixed = rng.integers(1, config.vocab_size,
                             (8, args.batch, args.seq_len)).astype(np.int32)
        n_batches = len(fixed)
        get = lambda i: fixed[i]  # noqa: E731
        print(f"data: {n_batches} synthetic batches (no --data-path)",
              flush=True)
    if args.max_batches:
        n_batches = min(n_batches, args.max_batches)

    batch_sharding = rules.sharding(mesh, "batch", None)

    def to_global(local):
        # a plain device_put of host-local rows cannot reshard onto
        # other processes' non-addressable devices on multi-host meshes
        if n_proc == 1:
            return jax.device_put(np.asarray(local), batch_sharding)
        return jax.make_array_from_process_local_data(
            batch_sharding, np.asarray(local),
            (global_batch, args.seq_len))

    total_nll = 0.0
    t0 = None
    for i in range(n_batches):
        # loss_fn is mean next-token CE over (seq_len - 1) positions
        total_nll += float(jax.device_get(eval_step(params, to_global(get(i)))))
        if t0 is None:
            t0 = time.time()  # steady-state clock: exclude batch 0's compile
        if args.log_every and ((i + 1) % args.log_every == 0
                               or i + 1 == n_batches):
            mean = total_nll / (i + 1)
            print(f"batch {i + 1}/{n_batches}: nll={mean:.4f} "
                  f"ppl={math.exp(min(mean, 30.0)):.2f}", flush=True)
    mean_nll = total_nll / n_batches
    tokens = n_batches * global_batch * (args.seq_len - 1)
    dt = max(time.time() - (t0 or time.time()), 1e-9)
    print(json.dumps({
        "metric": "eval_perplexity",
        "perplexity": round(math.exp(min(mean_nll, 30.0)), 4),
        "nll": round(mean_nll, 6),
        "tokens": tokens,
        # steady-state rate: the first batch (jit compile) starts the
        # clock but isn't counted in it
        "tokens_per_sec": round(
            (tokens - global_batch * (args.seq_len - 1)) / dt
            if n_batches > 1 else 0.0, 0),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
