"""GRPO post-training workload — prompts in, reward-tuned policy out.

JAXJob-deployable CLI over train/rl.py: reads JSONL prompts, samples G
completions per prompt from the CURRENT policy with the KV-cache decode
stack (models/decode.generate — one compiled dispatch per rollout
batch), scores them with a pluggable reward, and runs the sharded GRPO
update (mesh from KUBEDL_MESH like the trainer). Checkpoints the FULL
policy TrainState so generate/serve restore it with the ordinary
--checkpoint-path.

Data format — one JSON object per line:

    {"prompt": [ids...]}

With --hf-model the prompt may also be a raw string, encoded by the
checkpoint's own tokenizer.

Rewards (pick one):
  --reward token-match   fraction of completion tokens == --reward-token
                         (trivially learnable; smoke/CI default)
  --reward length        -|gen_len - --target-len| / max-new-tokens,
                         gen_len = tokens before the first --eos-id
  --reward-module m:fn   import m, call fn(prompt_ids, completion_ids)
                         -> float per completion (real use: verifiers,
                         reward models)

The frozen KL reference is the STARTING policy (base weights from
--hf-model / --ref-checkpoint-path / fresh init), as in DPO.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubedl-grpo")
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"),
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"])
    p.add_argument("--hf-model", default=os.environ.get("KUBEDL_HF_MODEL", ""),
                   help="Hugging Face base weights (policy AND reference init)")
    p.add_argument("--ref-checkpoint-path", default="",
                   help="trainer Orbax dir for the base weights (else fresh "
                        "init / --hf-model)")
    p.add_argument("--data-path", default=os.environ.get("KUBEDL_DATA_PATH", ""),
                   help="JSONL prompts; synthetic prompts when empty")
    p.add_argument("--steps", type=int,
                   default=int(os.environ.get("KUBEDL_STEPS", 50)),
                   help="rollout->update iterations")
    p.add_argument("--prompts-per-step", type=int, default=4)
    p.add_argument("--group-size", type=int, default=8,
                   help="G completions sampled per prompt")
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--inner-epochs", type=int, default=1,
                   help="updates per rollout batch (ratio clipping only "
                        "bites past the first)")
    p.add_argument("--lr", type=float, default=1e-6)
    p.add_argument("--clip-eps", type=float, default=0.2)
    p.add_argument("--kl-coef", type=float, default=0.04)
    p.add_argument("--grad-clip", type=float, default=1.0)
    p.add_argument("--accum-steps", type=int, default=1)
    p.add_argument("--reward", default="token-match",
                   choices=["token-match", "length"])
    p.add_argument("--reward-token", type=int, default=5)
    p.add_argument("--target-len", type=int, default=16)
    p.add_argument("--eos-id", type=int, default=-1,
                   help=">=0: completions end at the first occurrence "
                        "(trims seq_lens and the length reward)")
    p.add_argument("--reward-module", default="",
                   help="'module.path:fn' overriding --reward")
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""))
    p.add_argument("--checkpoint-interval", type=int, default=50)
    p.add_argument("--allow-fresh-init", action="store_true",
                   help="train from random base weights when no "
                        "--hf-model/--ref-checkpoint-path weights exist")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.reward == "length" and not args.reward_module and args.eos_id < 0:
        p.error("--reward length needs --eos-id: without a stop token "
                "every completion is exactly --max-new-tokens long, every "
                "group's reward is constant, and training is a no-op")
    if args.temperature <= 0:
        p.error("--temperature must be > 0: greedy rollouts make all G "
                "samples of a group identical, which zeroes every "
                "group-normalized advantage")
    if args.group_size < 2:
        p.error("--group-size must be >= 2: the group mean is the "
                "baseline, so a single sample always has advantage 0 and "
                "the policy gradient vanishes")
    if args.inner_epochs > 1 and args.accum_steps > 1:
        p.error("--inner-epochs > 1 with --accum-steps > 1: MultiSteps "
                "defers the param update across micro-steps, so inner "
                "epochs would recompute identical gradients (params "
                "unchanged between them) — use one or the other")
    return args


def load_prompts(path: str, limit_len: int, tokenizer=None):
    """JSONL -> list of id-lists; prompts longer than limit_len are
    skipped with a count. Prompts may be id lists or (with a tokenizer
    from --hf-model) raw strings."""
    from kubedl_tpu.train.generate import encode_field

    prompts, skipped = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ids = encode_field(json.loads(line)["prompt"], tokenizer,
                               "prompt")
            if not ids or len(ids) > limit_len:
                skipped += 1
                continue
            prompts.append(ids)
    if skipped:
        print(f"data: skipped {skipped} prompts over {limit_len} tokens",
              flush=True)
    if not prompts:
        raise ValueError(f"no usable prompts in {path}")
    return prompts


def make_reward_fn(args):
    """(prompt_ids, completion_ids) -> float. completion_ids is already
    EOS-trimmed when --eos-id is set."""
    if args.reward_module:
        mod_name, _, fn_name = args.reward_module.partition(":")
        fn = getattr(importlib.import_module(mod_name), fn_name or "reward")
        return fn
    if args.reward == "token-match":
        tok = args.reward_token

        def token_match(prompt_ids, completion_ids):
            if not completion_ids:
                return 0.0
            return sum(1 for t in completion_ids if t == tok) / len(completion_ids)

        return token_match

    def length_reward(prompt_ids, completion_ids):
        return -abs(len(completion_ids) - args.target_len) / max(
            args.max_new_tokens, 1)

    return length_reward


def main(argv=None) -> int:
    args = parse_args(argv)

    from kubedl_tpu.train import coordinator

    coordinator.initialize()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubedl_tpu.models import decode, llama
    from kubedl_tpu.parallel.mesh import ShardingRules, build_mesh_from_env
    from kubedl_tpu.train.rl import group_advantages, make_grpo_step

    tokenizer = None
    if args.hf_model:
        from kubedl_tpu.models.import_hf import load_hf

        base, config = load_hf(args.hf_model)
        from kubedl_tpu.train.generate import load_tokenizer

        tokenizer = load_tokenizer(args.hf_model)
    else:
        config = llama.LlamaConfig.config_for(args.model)
        from kubedl_tpu.train.generate import restore_or_init

        base = restore_or_init(
            config, args.ref_checkpoint_path,
            allow_fresh_init=(args.allow_fresh_init
                              or not args.ref_checkpoint_path),
            seed=args.seed, label="base")
        if base is None:
            return 1
    mesh = build_mesh_from_env()
    rules = ShardingRules()
    print(f"mesh: {dict(mesh.shape)} model={args.hf_model or args.model} "
          f"G={args.group_size} kl={args.kl_coef}", flush=True)

    tx = optax.adamw(args.lr, weight_decay=0.0)
    if args.grad_clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(args.grad_clip), tx)
    # one update per rollout (the default) is strictly on-policy: the
    # loss substitutes stop_gradient of its own forward for old_lp and
    # the dedicated sampling-time logprob pass is skipped entirely
    use_old = args.inner_epochs > 1
    init_state, lp_fn, ref_fn, step = make_grpo_step(
        base, config, tx, mesh, rules=rules, clip_eps=args.clip_eps,
        kl_coef=args.kl_coef, accum_steps=args.accum_steps,
        use_old_logprobs=use_old,
    )
    state = init_state(jax.tree.map(jnp.asarray, base))
    del base

    rng = np.random.default_rng(args.seed)
    max_prompt = config.max_seq_len - args.max_new_tokens
    if args.data_path:
        prompts = load_prompts(args.data_path, max_prompt,
                                tokenizer=tokenizer)
        print(f"data: {len(prompts)} prompts from {args.data_path}", flush=True)
    else:
        n = max(args.prompts_per_step * 4, 16)
        plen = min(16, max_prompt)
        prompts = [list(rng.integers(1, config.vocab_size, plen))
                   for _ in range(n)]
        print(f"data: {n} synthetic prompts (no --data-path)", flush=True)

    reward_fn = make_reward_fn(args)
    uniform = len({len(p) for p in prompts}) == 1
    pad_to = max(len(p) for p in prompts)
    K = args.max_new_tokens
    temp = args.temperature  # parse_args rejects <= 0 (group collapse)

    # off-policy (inner epochs): the behavior log-probs ride OUT of the
    # rollout itself — free at sample time (one gather next to the
    # sampling op) where the old dedicated lp_fn pass cost a full
    # forward per step. lp_fn stays available as the parity oracle
    # (tests/test_rl.py pins emitted == recomputed within tolerance).
    @jax.jit
    def rollout_uniform(p, toks, key):
        return decode.generate(p, toks, config, K, temperature=temp,
                               key=key, with_logprobs=use_old)

    @jax.jit
    def rollout_ragged(p, toks, lengths, key):
        return decode.generate(p, toks, config, K, temperature=temp,
                               key=key, lengths=lengths,
                               with_logprobs=use_old)

    mngr = None
    start_step = 0
    if args.checkpoint_path:
        import orbax.checkpoint as ocp

        mngr = ocp.CheckpointManager(
            args.checkpoint_path,
            options=ocp.CheckpointManagerOptions(max_to_keep=2, create=True),
        )
        latest = mngr.latest_step()
        if latest is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
            state = mngr.restore(latest, args=ocp.args.StandardRestore(abstract))
            start_step = latest
            print(f"restored policy checkpoint at step {start_step}", flush=True)

    import time

    B, G = args.prompts_per_step, args.group_size
    t0 = time.time()
    base_key = jax.random.PRNGKey(args.seed)
    for it in range(start_step + 1, args.steps + 1):
        # -- rollout: B prompts x G samples, one compiled dispatch.
        # Prompt picks and sampling keys are derived from the STEP
        # index, so preemption resume at `latest` continues the data/
        # noise schedule instead of replaying it from step 1 ------------
        it_rng = np.random.default_rng((args.seed, it))
        pick = it_rng.choice(len(prompts), size=B, replace=len(prompts) < B)
        batch_prompts = [prompts[i] for i in pick]
        plens = np.array([len(p) for p in batch_prompts], np.int32)
        toks = np.zeros((B, pad_to), np.int32)
        for i, p in enumerate(batch_prompts):
            toks[i, :len(p)] = p
        tiled = np.repeat(toks, G, axis=0)          # [B*G, pad_to]
        tiled_plens = np.repeat(plens, G)           # [B*G]
        sub = jax.random.fold_in(base_key, it)
        if uniform:
            rolled = rollout_uniform(state.params, jnp.asarray(tiled), sub)
        else:
            rolled = rollout_ragged(state.params, jnp.asarray(tiled),
                                    jnp.asarray(tiled_plens), sub)
        if use_old:
            comp, beh_lp = (np.asarray(rolled[0]), np.asarray(rolled[1]))
        else:
            comp = np.asarray(rolled)               # [B*G, K]

        # -- rewards + group-normalized advantages (host) -----------------
        n = B * G
        full = np.zeros((n, pad_to + K), np.int32)
        seq_lens = np.zeros(n, np.int32)
        rewards = np.zeros(n, np.float32)
        if use_old:
            # sampling-time logprobs into the sequence_logprobs grid:
            # index i holds log p(token i+1), so completion token j of a
            # row with prompt length pl lands at pl - 1 + j; positions
            # outside the completion stay 0 and are masked by the loss
            old_grid = np.zeros((n, pad_to + K - 1), np.float32)
        for i in range(n):
            pl = tiled_plens[i]
            c = comp[i]
            if args.eos_id >= 0:
                hits = np.nonzero(c == args.eos_id)[0]
                # reward sees the text BEFORE the stop token; training
                # keeps the stop token itself, so emitting EOS is an
                # action the policy gradient can credit (a length
                # reward is unlearnable otherwise)
                gen = c[: hits[0]] if len(hits) else c
                train_c = c[: hits[0] + 1] if len(hits) else c
            else:
                gen = train_c = c
            full[i, :pl] = tiled[i, :pl]
            full[i, pl:pl + len(train_c)] = train_c
            seq_lens[i] = pl + len(train_c)
            rewards[i] = reward_fn(list(tiled[i, :pl]), list(gen))
            if use_old:
                old_grid[i, pl - 1:pl - 1 + len(train_c)] = (
                    beh_lp[i, :len(train_c)])
        adv = np.asarray(
            group_advantages(rewards.reshape(B, G))).reshape(n)

        # -- ref (+ old, when off-policy) logprobs, then the update(s) ----
        lp_batch = (jnp.asarray(full), jnp.asarray(tiled_plens),
                    jnp.asarray(seq_lens))
        ref_lp = ref_fn(lp_batch)
        if use_old:
            # old_lp comes from the rollout (sampling-time capture), not
            # a second forward — lp_fn remains the parity oracle only
            train_batch = (*lp_batch, jnp.asarray(adv),
                           jnp.asarray(old_grid), ref_lp)
        else:
            train_batch = (*lp_batch, jnp.asarray(adv), ref_lp)
        for _ in range(args.inner_epochs):
            state, metrics = step(state, train_batch)

        if it % args.log_every == 0 or it == args.steps:
            m = {k: float(v) for k, v in metrics.items()}
            print(f"step {it}: reward={rewards.mean():.3f}"
                  f"+-{rewards.std():.3f} loss={m['loss']:.4f} "
                  f"kl={m['kl']:.4f} clip={m['clip_frac']:.2f}", flush=True)
        if mngr is not None and (it % args.checkpoint_interval == 0
                                 or it == args.steps):
            mngr.save(it, args=ocp.args.StandardSave(state))
    if mngr is not None:
        mngr.wait_until_finished()
        print(f"saved policy checkpoint at step {args.steps}", flush=True)
    print(f"done: {args.steps - start_step} steps in "
          f"{time.time() - t0:.1f}s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
