"""Batch generation — the inference companion to train/trainer.py.

Runs as a JAXJob pod program (or standalone): restores params from the
trainer's Orbax checkpoint when given one (otherwise fresh init), then
generates with the KV-cache decode path (models/decode.py — one-pass
flash prefill + lax.scan token loop, so the whole generation is a single
compiled dispatch) and prints throughput.

The reference has no serving path at all (it orchestrates training
frameworks); this makes the train -> checkpoint -> serve loop a
first-class job program on the same operator.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser("kubedl-generate")
    p.add_argument("--model", default=os.environ.get("KUBEDL_MODEL", "tiny"),
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"])
    p.add_argument("--checkpoint-path",
                   default=os.environ.get("KUBEDL_CHECKPOINT_PATH", ""),
                   help="trainer Orbax dir; newest step's params are used")
    p.add_argument("--hf-model", default=os.environ.get("KUBEDL_HF_MODEL", ""),
                   help="Hugging Face Llama name/dir — overrides --model/"
                        "--checkpoint-path (models/import_hf.py)")
    p.add_argument("--allow-fresh-init", action="store_true",
                   help="serve from random weights when --checkpoint-path "
                        "holds no checkpoint (otherwise that's an error)")
    p.add_argument("--lora-checkpoint-path", default="",
                   help="merge the newest adapter checkpoint from a trainer "
                        "--lora-rank run into the base weights (models/lora.py)")
    p.add_argument("--lora-alpha", type=float, default=None)
    p.add_argument("--batch", type=int, default=int(os.environ.get("KUBEDL_BATCH", 8)))
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 serving (models/quant.py): halves "
                        "the per-token HBM weight read on the bandwidth-"
                        "bound decode loop; per-output-channel scales")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache: half the cache memory and read "
                        "traffic at long contexts; per-position scales fold "
                        "exactly into the attention einsums")
    p.add_argument("--speculative-k", type=int, default=0,
                   help="speculative decoding: a draft model proposes K "
                        "tokens per target verify pass (batch must be 1). "
                        "At --temperature 0 the output is exactly the "
                        "target's greedy continuation; with temperature>0 "
                        "rejection sampling preserves the target's sampling "
                        "distribution")
    p.add_argument("--draft-model", default="tiny",
                   choices=["tiny", "bench-150m", "bench-1b", "llama-7b"],
                   help="draft model config for --speculative-k")
    p.add_argument("--draft-checkpoint-path", default="",
                   help="Orbax dir for draft params (fresh init if empty)")
    return p.parse_args(argv)


def restore_params(path, label="params"):
    """Newest checkpoint's params under `path`, or None if empty.

    The trainer saves the full TrainState, whose pytree flattens to
    (params, opt_state, step) — an untargeted restore returns that
    as a list; keep the params and drop the optimizer."""
    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    mngr = ocp.CheckpointManager(path)
    latest = mngr.latest_step()
    if latest is None:
        return None
    try:
        restored = mngr.restore(latest)
    except KeyError:
        # orbax >= 0.5 no longer infers the handler for a StandardSave'd
        # item on an untargeted restore ('Item "default" ... could not be
        # restored'); ask for the standard pytree restore explicitly
        restored = mngr.restore(latest, args=ocp.args.StandardRestore())
    if isinstance(restored, (list, tuple)):
        tree = restored[0]
    elif hasattr(restored, "params"):
        tree = restored.params
    else:
        tree = restored["params"]
    print(f"restored {label} params from checkpoint step {latest}", flush=True)
    return jax.tree.map(jnp.asarray, tree)


def load_tokenizer(hf_model: str):
    """AutoTokenizer for an --hf-model, or None with a warning (the
    id-list APIs still work) — the one tokenizer-loading block shared by
    serve/dpo/grpo."""
    if not hf_model:
        return None
    try:
        import transformers

        return transformers.AutoTokenizer.from_pretrained(hf_model)
    except Exception as e:  # noqa: BLE001 — id-list data still works
        print(f"no tokenizer loaded ({e}); id-list data only", flush=True)
        return None


def encode_field(value, tokenizer, field: str, continuation: bool = False):
    """JSONL field -> token ids: id lists pass through; strings encode
    via the tokenizer. Prompts encode with the tokenizer's special
    tokens (matching how serve.py encodes request text, so the trained
    prompt distribution is the served one); continuations (chosen/
    rejected/completions) never get BOS/EOS spliced mid-sequence."""
    if isinstance(value, str):
        if tokenizer is None:
            raise ValueError(
                f"{field!r} is text but no tokenizer is available — "
                f"pass --hf-model (and check the 'no tokenizer loaded' "
                f"warning if you already did), or pre-tokenize to id "
                f"lists")
        return list(tokenizer.encode(value,
                                     add_special_tokens=not continuation))
    return [int(t) for t in value]


def resolve_params(model, hf_model, checkpoint_path, allow_fresh_init,
                   lora_checkpoint_path="", lora_alpha=None, seed=0,
                   label="target"):
    """The shared weight-resolution cascade of the generate/serve/
    evaluate entrypoints: --hf-model beats --model/--checkpoint-path,
    then an optional LoRA merge. Returns (params, config), or
    (None, None) when a required checkpoint is missing (the error is
    already printed)."""
    if hf_model:
        from kubedl_tpu.models.import_hf import load_hf

        params, config = load_hf(hf_model)
    else:
        from kubedl_tpu.models import llama

        config = llama.LlamaConfig.config_for(model)
        params = restore_or_init(config, checkpoint_path, allow_fresh_init,
                                 seed=seed, label=label)
        if params is None:
            return None, None
    if lora_checkpoint_path:
        from kubedl_tpu.models.lora import restore_and_merge

        params = restore_and_merge(params, lora_checkpoint_path,
                                   alpha=lora_alpha)
    return params, config


def restore_or_init(config, checkpoint_path, allow_fresh_init, seed=0,
                    label="target"):
    """Checkpoint params, fresh init, or None (error already printed) —
    shared by the generate and serve workload entrypoints."""
    import jax

    from kubedl_tpu.models import llama

    params = None
    if checkpoint_path:
        params = restore_params(checkpoint_path, label)
        if params is None:
            if not allow_fresh_init:
                # An explicit checkpoint path with nothing under it means a
                # missing volume mount or a wrong dir — serving random
                # weights with exit 0 would hide that.
                print(f"error: no checkpoint under {checkpoint_path} "
                      f"(pass --allow-fresh-init to serve random weights)",
                      file=sys.stderr)
                return None
            print(f"no checkpoint under {checkpoint_path}; using fresh init",
                  flush=True)
    if params is None:
        # init only when actually serving fresh weights — a 7B init would
        # double peak memory next to a restored checkpoint
        params = llama.init(config, jax.random.PRNGKey(seed))
    return params


def main(argv=None) -> int:
    args = parse_args(argv)

    from kubedl_tpu.train import coordinator

    coordinator.initialize()

    import jax
    import jax.numpy as jnp

    from kubedl_tpu.models import decode, llama

    params, config = resolve_params(
        args.model, args.hf_model, args.checkpoint_path,
        args.allow_fresh_init, lora_checkpoint_path=args.lora_checkpoint_path,
        lora_alpha=args.lora_alpha, seed=args.seed)
    if params is None:
        return 1

    if args.int8:
        from kubedl_tpu.models import quant

        before = quant.tree_bytes(params)
        params = jax.jit(quant.quantize_params)(params)
        after = quant.tree_bytes(params)
        print(f"int8: params {before / 1e6:.0f} MB -> {after / 1e6:.0f} MB "
              f"(whole tree incl. unquantized embedding)", flush=True)

    prompt = jax.random.randint(
        jax.random.PRNGKey(args.seed + 1),
        (args.batch, args.prompt_len), 0, config.vocab_size,
    )
    kv_dtype = "int8" if args.kv_int8 else None
    if args.speculative_k:
        if args.speculative_k < 2:
            print("error: --speculative-k must be >= 2 (k=1 degenerates to "
                  "vanilla greedy with an extra draft pass)", file=sys.stderr)
            return 2
        if args.batch != 1:
            print("error: --speculative-k requires --batch 1", file=sys.stderr)
            return 2
        draft_config = llama.LlamaConfig.config_for(args.draft_model)
        if draft_config.vocab_size != config.vocab_size:
            print(f"error: --draft-model {args.draft_model} vocab "
                  f"{draft_config.vocab_size} != target vocab "
                  f"{config.vocab_size}; the models must share a tokenizer",
                  file=sys.stderr)
            return 2
        draft = None
        if args.draft_checkpoint_path:
            draft = restore_params(args.draft_checkpoint_path, "draft")
            if draft is None:
                if not args.allow_fresh_init:
                    # same policy as the target path: an empty draft dir
                    # means a missing mount — a silent random draft would
                    # just make speculation slower than vanilla with exit 0
                    print(f"error: no checkpoint under "
                          f"{args.draft_checkpoint_path} "
                          f"(pass --allow-fresh-init for a random draft)",
                          file=sys.stderr)
                    return 1
                print(f"no checkpoint under {args.draft_checkpoint_path}; "
                      f"using fresh draft init", flush=True)
        if draft is None:
            draft = llama.init(draft_config, jax.random.PRNGKey(args.seed + 3))
        if args.int8:
            from kubedl_tpu.models import quant

            draft = jax.jit(quant.quantize_params)(draft)
        spec_gen = jax.jit(lambda p, dp, pr, kk: decode.generate_speculative(
            p, dp, pr, config, draft_config,
            max_new_tokens=args.max_new_tokens, k=args.speculative_k,
            kv_dtype=kv_dtype, return_stats=True,
            temperature=args.temperature, key=kk,
        ))
        spec_stats = {}

        def gen(p, pr, key):
            toks, stats = spec_gen(p, draft, pr, key)
            spec_stats.update(stats)
            return toks
    else:
        gen = jax.jit(lambda p, pr, key: decode.generate(
            p, pr, config,
            max_new_tokens=args.max_new_tokens,
            max_len=args.prompt_len + args.max_new_tokens,
            temperature=args.temperature, key=key,
            kv_dtype=kv_dtype,
        ))
    key = jax.random.PRNGKey(args.seed + 2)

    t0 = time.perf_counter()
    toks = jax.device_get(gen(params, prompt, key))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    toks = jax.device_get(gen(params, prompt, key))
    dt = max(time.perf_counter() - t0, 1e-9)

    total = args.batch * args.max_new_tokens
    print(f"sample[0,:8]={list(map(int, toks[0][:8]))}", flush=True)
    if args.speculative_k:
        print(f"speculative: rounds={int(spec_stats['rounds'])} "
              f"acceptance={float(spec_stats['acceptance']):.2f}", flush=True)
    print(f"done: generated {args.batch}x{args.max_new_tokens} tokens in "
          f"{dt:.2f}s ({total / dt:.0f} tok/s, compile {compile_s:.1f}s)",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
